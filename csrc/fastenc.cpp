// fastenc — native single-pass AdmissionReview JSON → feature-tensor encoder.
//
// The TPU serving pipeline's host-side bottleneck is encoding (SURVEY.md §7.4
// hard-part #1): walking the request JSON and scattering leaves into the
// policy-derived feature arrays (ops/codec.py). This is the native
// implementation of exactly that codec: a minimal JSON parser fused with the
// extraction trie, writing numeric/bool/presence features straight into the
// caller's numpy buffers and collecting ID/pred strings into an arena for
// the (memoized, cheap) Python-side interning pass.
//
// Semantics mirror ops/codec.py bit for bit:
//   * dtype mismatches are "missing" (mask stays 0): ID wants a JSON string;
//     F32 wants a number (bool excluded); I32 wants a syntactic integer
//     (bool and floats excluded); BOOL wants true/false.
//   * presence marks non-null leaves; null is absent.
//   * a '*' axis over an object iterates {"__key__", "__value__"} wrappers
//     in SORTED key order (codec.star_elements).
//   * axis overflow aborts the encode with the offending array id (the
//     caller raises SchemaOverflow and falls back to a wider bucket or the
//     host oracle).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this environment).
// The entire encode runs without touching Python objects, so callers may
// release the GIL and encode batches on parallel threads.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <algorithm>
#include <memory>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// ----------------------------------------------------------------- schema --

enum Kind : int32_t { KIND_VALUE = 0, KIND_PRESENT = 1, KIND_PRED = 2 };
enum DType : int32_t { DT_ID = 0, DT_F32 = 1, DT_BOOL = 2, DT_I32 = 3 };

struct Terminal {
  int32_t array_id;   // index into the caller's buffer table
  int32_t kind;       // Kind
  int32_t dtype;      // DType (KIND_VALUE only)
  int32_t mask_id;    // mask buffer index (KIND_VALUE only, else -1)
  int32_t pred_id;    // string-pred id (KIND_PRED only, else -1)
};

struct Node {
  std::unordered_map<std::string, std::unique_ptr<Node>> children;
  std::unique_ptr<Node> star;
  std::vector<Terminal> terminals;
  int32_t axis_cap = 0;     // cap of the star axis rooted here
  int32_t overflow_id = -1; // representative array id for overflow errors
};

struct ArrayInfo {
  int32_t ndim;        // 0..2 element axes
  int32_t caps[2];     // axis capacities
  int32_t elsize;      // bytes per element in the caller buffer
  int64_t row_stride;  // batch-mode row stride in BYTES; 0 = contiguous
                       // (elems*elsize). Non-zero when the array is a
                       // column block of a wider packed batch buffer.
};

struct Schema {
  Node root;
  std::vector<ArrayInfo> arrays;
};

// ------------------------------------------------------ schema JSON parse --
// The schema description itself arrives as JSON (built once at boot by
// ops/fastenc.py); we reuse the same parser.

struct Parser;

// ------------------------------------------------------------ JSON parser --

struct Parser {
  const char* p;
  const char* end;
  bool ok = true;

  explicit Parser(const char* data, size_t n) : p(data), end(data + n) {}

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) p++;
  }
  bool lit(const char* s, size_t n) {
    if ((size_t)(end - p) < n || memcmp(p, s, n) != 0) return false;
    p += n;
    return true;
  }
  // Parse a JSON string (assumes *p == '"'); appends decoded bytes to out.
  bool str(std::string& out) {
    if (p >= end || *p != '"') return false;
    p++;
    while (p < end) {
      unsigned char c = (unsigned char)*p;
      if (c == '"') { p++; return true; }
      if (c == '\\') {
        p++;
        if (p >= end) return false;
        char e = *p++;
        switch (e) {
          case '"': out.push_back('"'); break;
          case '\\': out.push_back('\\'); break;
          case '/': out.push_back('/'); break;
          case 'b': out.push_back('\b'); break;
          case 'f': out.push_back('\f'); break;
          case 'n': out.push_back('\n'); break;
          case 'r': out.push_back('\r'); break;
          case 't': out.push_back('\t'); break;
          case 'u': {
            if (end - p < 4) return false;
            unsigned int cp = 0;
            for (int i = 0; i < 4; i++) {
              char h = p[i];
              cp <<= 4;
              if (h >= '0' && h <= '9') cp |= h - '0';
              else if (h >= 'a' && h <= 'f') cp |= h - 'a' + 10;
              else if (h >= 'A' && h <= 'F') cp |= h - 'A' + 10;
              else return false;
            }
            p += 4;
            // surrogate pair
            if (cp >= 0xD800 && cp <= 0xDBFF && end - p >= 6 && p[0] == '\\' &&
                p[1] == 'u') {
              unsigned int lo = 0;
              bool okp = true;
              for (int i = 0; i < 4; i++) {
                char h = p[2 + i];
                lo <<= 4;
                if (h >= '0' && h <= '9') lo |= h - '0';
                else if (h >= 'a' && h <= 'f') lo |= h - 'a' + 10;
                else if (h >= 'A' && h <= 'F') lo |= h - 'A' + 10;
                else { okp = false; break; }
              }
              if (okp && lo >= 0xDC00 && lo <= 0xDFFF) {
                p += 6;
                cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
              }
            }
            // UTF-8 encode
            if (cp < 0x80) out.push_back((char)cp);
            else if (cp < 0x800) {
              out.push_back((char)(0xC0 | (cp >> 6)));
              out.push_back((char)(0x80 | (cp & 0x3F)));
            } else if (cp < 0x10000) {
              out.push_back((char)(0xE0 | (cp >> 12)));
              out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
              out.push_back((char)(0x80 | (cp & 0x3F)));
            } else {
              out.push_back((char)(0xF0 | (cp >> 18)));
              out.push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
              out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
              out.push_back((char)(0x80 | (cp & 0x3F)));
            }
            break;
          }
          default: return false;
        }
      } else {
        out.push_back((char)c);
        p++;
      }
    }
    return false;
  }
  bool skip_string() {
    if (p >= end || *p != '"') return false;
    p++;
    while (p < end) {
      if (*p == '\\') { p += 2; continue; }
      if (*p == '"') { p++; return true; }
      p++;
    }
    return false;
  }
  bool skip_value() {
    ws();
    if (p >= end) return false;
    switch (*p) {
      case '"': return skip_string();
      case '{': {
        p++;
        ws();
        if (p < end && *p == '}') { p++; return true; }
        while (p < end) {
          ws();
          if (!skip_string()) return false;
          ws();
          if (p >= end || *p != ':') return false;
          p++;
          if (!skip_value()) return false;
          ws();
          if (p < end && *p == ',') { p++; continue; }
          if (p < end && *p == '}') { p++; return true; }
          return false;
        }
        return false;
      }
      case '[': {
        p++;
        ws();
        if (p < end && *p == ']') { p++; return true; }
        while (p < end) {
          if (!skip_value()) return false;
          ws();
          if (p < end && *p == ',') { p++; continue; }
          if (p < end && *p == ']') { p++; return true; }
          return false;
        }
        return false;
      }
      case 't': return lit("true", 4);
      case 'f': return lit("false", 5);
      case 'n': return lit("null", 4);
      default: {
        const char* start = p;
        while (p < end && (*p == '-' || *p == '+' || *p == '.' || *p == 'e' ||
                           *p == 'E' || (*p >= '0' && *p <= '9')))
          p++;
        return p > start;
      }
    }
  }
};

// ------------------------------------------------------------- the encode --

struct StringRecord {
  int32_t array_id;
  int32_t flat_offset;
  int32_t is_pred;   // 1 when this is a pred array cell
  int32_t pred_id;
  int32_t str_offset;
  int32_t str_len;
};

struct EncodeState {
  const Schema* schema;
  uint8_t** buffers;       // array_id -> destination buffer
  std::string arena;       // collected ID/pred strings
  std::vector<StringRecord> records;
  int32_t error_array = -1;  // set on axis overflow / unencodable value
  bool unencodable = false;  // out-of-range numeric → oracle fallback
  std::string scratch;
};

inline int32_t flat_offset(const ArrayInfo& a, const int32_t* coords,
                           int depth) {
  // coords has `depth` entries; arrays may have fewer axes than the current
  // walk depth never happens (trie guarantees alignment).
  int32_t off = 0;
  for (int i = 0; i < a.ndim; i++) off = off * a.caps[i] + coords[i];
  return off;
}

// Values parsed at a leaf position.
enum LeafType { LEAF_NULL, LEAF_BOOL, LEAF_INT, LEAF_FLOAT, LEAF_STR, LEAF_CONTAINER };

struct Leaf {
  LeafType type = LEAF_NULL;
  bool b = false;
  double num = 0.0;
  int64_t inum = 0;
  const std::string* s = nullptr;  // points into EncodeState scratch/owned
};

// Out-of-range numerics must NOT silently truncate or read as missing —
// either would give a different verdict than the oracle (fail-open). They
// abort the row's encode; the host routes the request to the oracle
// (mirrors codec.UnencodableValue).
inline bool fits_i32(int64_t v) {
  return v >= INT32_MIN && v <= INT32_MAX;
}
inline bool fits_f32(double v) {
  return v == v && v <= 3.4028235677973366e38 && v >= -3.4028235677973366e38;
}

bool emit_terminals(EncodeState& st, const Node& node, const Leaf& leaf,
                    const int32_t* coords, int depth) {
  for (const Terminal& t : node.terminals) {
    const ArrayInfo& a = st.schema->arrays[(size_t)t.array_id];
    int32_t off = flat_offset(a, coords, depth);
    switch (t.kind) {
      case KIND_PRESENT:
        if (leaf.type != LEAF_NULL)
          st.buffers[t.array_id][off] = 1;
        break;
      case KIND_PRED:
        if (leaf.type == LEAF_STR) {
          st.records.push_back({t.array_id, off, 1, t.pred_id,
                                (int32_t)st.arena.size(),
                                (int32_t)leaf.s->size()});
          st.arena.append(*leaf.s);
        }
        break;
      case KIND_VALUE: {
        uint8_t* buf = st.buffers[t.array_id];
        // mask_id == -1: the optimizer proved this column's validity
        // mask redundant (zero-fill folding) — no mask buffer exists
        uint8_t* mask = t.mask_id >= 0 ? st.buffers[t.mask_id] : nullptr;
        switch (t.dtype) {
          case DT_ID:
            if (leaf.type == LEAF_STR) {
              st.records.push_back({t.array_id, off, 0, -1,
                                    (int32_t)st.arena.size(),
                                    (int32_t)leaf.s->size()});
              st.arena.append(*leaf.s);
              if (mask) mask[off] = 1;
            }
            break;
          case DT_F32:
            if (leaf.type == LEAF_INT || leaf.type == LEAF_FLOAT) {
              double v =
                  leaf.type == LEAF_INT ? (double)leaf.inum : leaf.num;
              if (!fits_f32(v)) {
                st.unencodable = true;
                st.error_array = t.array_id;
                return false;
              }
              ((float*)buf)[off] = (float)v;
              if (mask) mask[off] = 1;
            }
            break;
          case DT_I32:
            if (leaf.type == LEAF_INT) {
              if (!fits_i32(leaf.inum)) {
                st.unencodable = true;
                st.error_array = t.array_id;
                return false;
              }
              ((int32_t*)buf)[off] = (int32_t)leaf.inum;
              if (mask) mask[off] = 1;
            }
            break;
          case DT_BOOL:
            if (leaf.type == LEAF_BOOL) {
              buf[off] = leaf.b ? 1 : 0;
              if (mask) mask[off] = 1;
            }
            break;
        }
        break;
      }
    }
  }
  return true;
}

// Forward decl.
bool walk(EncodeState& st, Parser& ps, const Node& node, int32_t* coords,
          int depth);

// Expand a '*' axis over the upcoming JSON value.
bool walk_star(EncodeState& st, Parser& ps, const Node& node, int32_t* coords,
               int depth) {
  ps.ws();
  if (ps.p >= ps.end) return false;
  const Node& star = *node.star;
  if (*ps.p == '[') {
    ps.p++;
    ps.ws();
    int32_t i = 0;
    if (ps.p < ps.end && *ps.p == ']') { ps.p++; return true; }
    while (ps.p < ps.end) {
      if (node.axis_cap && i >= node.axis_cap) {
        st.error_array = node.overflow_id;
        return false;
      }
      coords[depth] = i;
      if (!walk(st, ps, star, coords, depth + 1)) return false;
      i++;
      ps.ws();
      if (ps.p < ps.end && *ps.p == ',') { ps.p++; continue; }
      if (ps.p < ps.end && *ps.p == ']') { ps.p++; return true; }
      return false;
    }
    return false;
  }
  if (*ps.p == '{') {
    // Objects iterate {__key__, __value__} wrappers in SORTED key order; we
    // must buffer entries (key + raw value span) and re-walk them sorted.
    ps.p++;
    ps.ws();
    std::vector<std::pair<std::string, std::pair<const char*, const char*>>>
        entries;
    if (ps.p < ps.end && *ps.p == '}') {
      ps.p++;
    } else {
      while (ps.p < ps.end) {
        ps.ws();
        std::string key;
        if (!ps.str(key)) return false;
        ps.ws();
        if (ps.p >= ps.end || *ps.p != ':') return false;
        ps.p++;
        ps.ws();
        const char* vstart = ps.p;
        if (!ps.skip_value()) return false;
        entries.emplace_back(std::move(key), std::make_pair(vstart, ps.p));
        ps.ws();
        if (ps.p < ps.end && *ps.p == ',') { ps.p++; continue; }
        if (ps.p < ps.end && *ps.p == '}') { ps.p++; break; }
        return false;
      }
    }
    // Direct-key children coexist with the star expansion (e.g. both
    // metadata.labels[*] and metadata.labels.foo specs).
    if (!node.children.empty()) {
      for (auto& e : entries) {
        auto it = node.children.find(e.first);
        if (it != node.children.end()) {
          Parser sub(e.second.first,
                     (size_t)(e.second.second - e.second.first));
          if (!walk(st, sub, *it->second, coords, depth)) return false;
        }
      }
    }
    std::sort(entries.begin(), entries.end(),
              [](const auto& a, const auto& b) { return a.first < b.first; });
    if (node.axis_cap && (int32_t)entries.size() > node.axis_cap) {
      st.error_array = node.overflow_id;
      return false;
    }
    int32_t i = 0;
    for (auto& e : entries) {
      coords[depth] = i++;
      // The wrapper "element": terminals on the star node see a container.
      Leaf leaf;
      leaf.type = LEAF_CONTAINER;
      if (!emit_terminals(st, star, leaf, coords, depth + 1)) return false;
      // __key__ child
      auto kit = star.children.find("__key__");
      if (kit != star.children.end()) {
        Leaf kl;
        kl.type = LEAF_STR;
        kl.s = &e.first;
        if (!emit_terminals(st, *kit->second, kl, coords, depth + 1))
          return false;
        // __key__ has no deeper structure (it is a string)
      }
      // __value__ child: re-parse the buffered span
      auto vit = star.children.find("__value__");
      if (vit != star.children.end()) {
        Parser sub(e.second.first, (size_t)(e.second.second - e.second.first));
        if (!walk(st, sub, *vit->second, coords, depth + 1)) return false;
      }
      if (star.star) {
        // nested quantifier over the value (e.g. map value is an array):
        // matches codec semantics where the wrapper itself is the element
        // and deeper stars come from Elem sub-paths — wrapper dicts have no
        // direct star expansion.
      }
    }
    return true;
  }
  // Scalar under a star domain: not iterable — nothing to expand.
  return ps.skip_value();
}

bool walk(EncodeState& st, Parser& ps, const Node& node, int32_t* coords,
          int depth) {
  ps.ws();
  if (ps.p >= ps.end) return false;
  char c = *ps.p;

  // Leaf-typed values: emit terminals, no deeper traversal.
  if (c == '"') {
    st.scratch.clear();
    if (!ps.str(st.scratch)) return false;
    Leaf leaf;
    leaf.type = LEAF_STR;
    leaf.s = &st.scratch;
    if (!emit_terminals(st, node, leaf, coords, depth)) return false;
    return true;
  }
  if (c == 't' || c == 'f') {
    Leaf leaf;
    leaf.type = LEAF_BOOL;
    leaf.b = (c == 't');
    if (!(leaf.b ? ps.lit("true", 4) : ps.lit("false", 5))) return false;
    if (!emit_terminals(st, node, leaf, coords, depth)) return false;
    return true;
  }
  if (c == 'n') {
    if (!ps.lit("null", 4)) return false;
    Leaf leaf;  // LEAF_NULL
    if (!emit_terminals(st, node, leaf, coords, depth)) return false;
    return true;
  }
  if (c == '-' || (c >= '0' && c <= '9')) {
    const char* start = ps.p;
    bool is_float = false;
    while (ps.p < ps.end &&
           (*ps.p == '-' || *ps.p == '+' || *ps.p == '.' || *ps.p == 'e' ||
            *ps.p == 'E' || (*ps.p >= '0' && *ps.p <= '9'))) {
      if (*ps.p == '.' || *ps.p == 'e' || *ps.p == 'E') is_float = true;
      ps.p++;
    }
    std::string num(start, (size_t)(ps.p - start));
    Leaf leaf;
    if (is_float) {
      leaf.type = LEAF_FLOAT;
      leaf.num = strtod(num.c_str(), nullptr);
    } else {
      leaf.type = LEAF_INT;
      leaf.inum = strtoll(num.c_str(), nullptr, 10);
    }
    if (!emit_terminals(st, node, leaf, coords, depth)) return false;
    return true;
  }

  // Containers: presence terminals fire, then children / star.
  Leaf leaf;
  leaf.type = LEAF_CONTAINER;
  if (!emit_terminals(st, node, leaf, coords, depth)) return false;

  if (c == '{') {
    if (node.star) {
      // star over an object — handled by walk_star (it re-reads from p)
      return walk_star(st, ps, node, coords, depth);
    }
    ps.p++;
    ps.ws();
    if (ps.p < ps.end && *ps.p == '}') { ps.p++; return true; }
    while (ps.p < ps.end) {
      ps.ws();
      st.scratch.clear();
      std::string key;
      if (!ps.str(key)) return false;
      ps.ws();
      if (ps.p >= ps.end || *ps.p != ':') return false;
      ps.p++;
      auto it = node.children.find(key);
      if (it != node.children.end()) {
        if (!walk(st, ps, *it->second, coords, depth)) return false;
      } else {
        if (!ps.skip_value()) return false;
      }
      ps.ws();
      if (ps.p < ps.end && *ps.p == ',') { ps.p++; continue; }
      if (ps.p < ps.end && *ps.p == '}') { ps.p++; return true; }
      return false;
    }
    return false;
  }
  if (c == '[') {
    if (node.star) return walk_star(st, ps, node, coords, depth);
    return ps.skip_value();  // array where schema expects object: skip
  }
  return false;
}

// ------------------------------------------------- schema JSON description --

// Minimal DOM for the schema description (parsed once at boot; clarity over
// speed here).
struct SVal {
  enum T { OBJ, ARR, STR, NUM, BOOL_, NUL } t = NUL;
  std::unordered_map<std::string, std::unique_ptr<SVal>> obj;
  std::vector<std::unique_ptr<SVal>> arr;
  std::string s;
  double num = 0;
  bool b = false;
};

std::unique_ptr<SVal> parse_sval(Parser& ps) {
  ps.ws();
  auto v = std::make_unique<SVal>();
  if (ps.p >= ps.end) return nullptr;
  char c = *ps.p;
  if (c == '{') {
    v->t = SVal::OBJ;
    ps.p++;
    ps.ws();
    if (ps.p < ps.end && *ps.p == '}') { ps.p++; return v; }
    while (ps.p < ps.end) {
      ps.ws();
      std::string key;
      if (!ps.str(key)) return nullptr;
      ps.ws();
      if (ps.p >= ps.end || *ps.p != ':') return nullptr;
      ps.p++;
      auto child = parse_sval(ps);
      if (!child) return nullptr;
      v->obj.emplace(std::move(key), std::move(child));
      ps.ws();
      if (ps.p < ps.end && *ps.p == ',') { ps.p++; continue; }
      if (ps.p < ps.end && *ps.p == '}') { ps.p++; return v; }
      return nullptr;
    }
    return nullptr;
  }
  if (c == '[') {
    v->t = SVal::ARR;
    ps.p++;
    ps.ws();
    if (ps.p < ps.end && *ps.p == ']') { ps.p++; return v; }
    while (ps.p < ps.end) {
      auto child = parse_sval(ps);
      if (!child) return nullptr;
      v->arr.push_back(std::move(child));
      ps.ws();
      if (ps.p < ps.end && *ps.p == ',') { ps.p++; continue; }
      if (ps.p < ps.end && *ps.p == ']') { ps.p++; return v; }
      return nullptr;
    }
    return nullptr;
  }
  if (c == '"') {
    v->t = SVal::STR;
    if (!ps.str(v->s)) return nullptr;
    return v;
  }
  if (c == 't') { v->t = SVal::BOOL_; v->b = true; return ps.lit("true", 4) ? std::move(v) : nullptr; }
  if (c == 'f') { v->t = SVal::BOOL_; v->b = false; return ps.lit("false", 5) ? std::move(v) : nullptr; }
  if (c == 'n') { v->t = SVal::NUL; return ps.lit("null", 4) ? std::move(v) : nullptr; }
  v->t = SVal::NUM;
  const char* start = ps.p;
  while (ps.p < ps.end && (*ps.p == '-' || *ps.p == '+' || *ps.p == '.' ||
                           *ps.p == 'e' || *ps.p == 'E' ||
                           (*ps.p >= '0' && *ps.p <= '9')))
    ps.p++;
  if (ps.p == start) return nullptr;
  v->num = strtod(std::string(start, (size_t)(ps.p - start)).c_str(), nullptr);
  return v;
}

bool build_node(const SVal& desc, Node& out) {
  auto ti = desc.obj.find("terminals");
  if (ti != desc.obj.end()) {
    for (const auto& t : ti->second->arr) {
      Terminal term;
      term.array_id = (int32_t)t->obj.at("array")->num;
      term.kind = (int32_t)t->obj.at("kind")->num;
      term.dtype = (int32_t)t->obj.at("dtype")->num;
      term.mask_id = (int32_t)t->obj.at("mask")->num;
      term.pred_id = (int32_t)t->obj.at("pred")->num;
      out.terminals.push_back(term);
    }
  }
  auto ci = desc.obj.find("children");
  if (ci != desc.obj.end()) {
    for (const auto& kv : ci->second->obj) {
      auto child = std::make_unique<Node>();
      if (!build_node(*kv.second, *child)) return false;
      out.children.emplace(kv.first, std::move(child));
    }
  }
  auto si = desc.obj.find("star");
  if (si != desc.obj.end() && si->second->t == SVal::OBJ) {
    out.star = std::make_unique<Node>();
    if (!build_node(*si->second, *out.star)) return false;
    out.axis_cap = (int32_t)desc.obj.at("axis_cap")->num;
    out.overflow_id = (int32_t)desc.obj.at("overflow_id")->num;
  }
  return true;
}

}  // namespace

// ------------------------------------------------------------------ C ABI --

extern "C" {

// Build a schema from its JSON description. Returns an opaque handle or null.
void* fastenc_create(const char* schema_json, int64_t len) {
  Parser ps(schema_json, (size_t)len);
  auto desc = parse_sval(ps);
  if (!desc || desc->t != SVal::OBJ) return nullptr;
  auto schema = std::make_unique<Schema>();
  for (const auto& a : desc->obj.at("arrays")->arr) {
    ArrayInfo info{};
    const auto& caps = a->obj.at("caps")->arr;
    info.ndim = (int32_t)caps.size();
    for (size_t i = 0; i < caps.size() && i < 2; i++)
      info.caps[i] = (int32_t)caps[i]->num;
    info.elsize = (int32_t)a->obj.at("elsize")->num;
    auto rs = a->obj.find("row_stride");
    info.row_stride = rs != a->obj.end() ? (int64_t)rs->second->num : 0;
    schema->arrays.push_back(info);
  }
  if (!build_node(*desc->obj.at("trie"), schema->root)) return nullptr;
  return schema.release();
}

void fastenc_destroy(void* handle) { delete (Schema*)handle; }

// Encode one JSON document.
//   buffers    — array of pointers, one per schema array (pre-zeroed!)
//   arena      — output buffer for ID/pred string bytes
//   arena_cap  — its capacity
//   records    — output buffer of int32 sextuples (see StringRecord)
//   records_cap— its capacity IN RECORDS
// Returns: >=0 — number of string records written;
//          -1 — JSON parse error; -2 — arena/records overflow;
//          -(1000+array_id) — axis cap overflow on array_id.
int64_t fastenc_encode(void* handle, const char* json, int64_t len,
                       uint8_t** buffers, uint8_t* arena, int64_t arena_cap,
                       int32_t* records, int64_t records_cap) {
  Schema* schema = (Schema*)handle;
  EncodeState st;
  st.schema = schema;
  st.buffers = buffers;
  Parser ps(json, (size_t)len);
  int32_t coords[4] = {0, 0, 0, 0};
  bool ok = walk(st, ps, schema->root, coords, 0);
  if (!ok) {
    if (st.error_array >= 0) return -(1000 + (int64_t)st.error_array);
    return -1;
  }
  if ((int64_t)st.arena.size() > arena_cap ||
      (int64_t)st.records.size() > records_cap)
    return -2;
  memcpy(arena, st.arena.data(), st.arena.size());
  memcpy(records, st.records.data(),
         st.records.size() * sizeof(StringRecord));
  return (int64_t)st.records.size();
}

// Encode a BATCH of JSON documents directly into batched (leading row axis)
// buffers — one call per dispatch, rows written in place, so the host never
// materializes per-request arrays or re-stacks them.
//   base_buffers — per-array base pointers of the batch arrays (pre-zeroed)
//   row_status   — per-row result: 0 ok, -1 parse error,
//                  -(1000+array_id) axis overflow (those rows are re-tried
//                  host-side on a wider bucket / the oracle)
//   records gain ABSOLUTE flat offsets (row * prod(caps) + local).
// Returns number of string records, or -2 on arena/records overflow.
int64_t fastenc_encode_batch(void* handle, const char** jsons,
                             const int64_t* lens, int64_t n_rows,
                             uint8_t** base_buffers, uint8_t* arena,
                             int64_t arena_cap, int32_t* records,
                             int64_t records_cap, int32_t* row_status) {
  Schema* schema = (Schema*)handle;
  size_t n_arrays = schema->arrays.size();
  std::vector<int64_t> stride_elems(n_arrays), block_bytes(n_arrays),
      row_stride_bytes(n_arrays);
  for (size_t i = 0; i < n_arrays; i++) {
    const ArrayInfo& a = schema->arrays[i];
    int64_t elems = 1;
    for (int d = 0; d < a.ndim; d++) elems *= a.caps[d];
    stride_elems[i] = elems;
    block_bytes[i] = elems * a.elsize;
    row_stride_bytes[i] = a.row_stride ? a.row_stride : block_bytes[i];
  }
  std::vector<uint8_t*> row_buffers(n_arrays);
  std::string arena_acc;
  std::vector<StringRecord> records_acc;
  // Batch-level string dedup: request corpora repeat names/images/keys
  // heavily, and the Python-side interning pass is O(#unique) after this.
  std::unordered_map<std::string, int32_t> interned;
  for (int64_t row = 0; row < n_rows; row++) {
    for (size_t i = 0; i < n_arrays; i++)
      row_buffers[i] = base_buffers[i] + row * row_stride_bytes[i];
    EncodeState st;
    st.schema = schema;
    st.buffers = row_buffers.data();
    Parser ps(jsons[row], (size_t)lens[row]);
    int32_t coords[4] = {0, 0, 0, 0};
    bool ok = walk(st, ps, schema->root, coords, 0);
    if (!ok) {
      row_status[row] =
          st.error_array >= 0 ? -(1000 + st.error_array) : -1;
      // wipe partial writes: the row still rides the batch dispatch and
      // must read as all-missing
      for (size_t i = 0; i < n_arrays; i++)
        memset(row_buffers[i], 0, (size_t)block_bytes[i]);
      continue;
    }
    row_status[row] = 0;
    for (StringRecord r : st.records) {
      std::string s(st.arena.data() + r.str_offset, (size_t)r.str_len);
      auto it = interned.find(s);
      int32_t off;
      if (it == interned.end()) {
        off = (int32_t)arena_acc.size();
        arena_acc.append(s);
        interned.emplace(std::move(s), off);
      } else {
        off = it->second;
      }
      r.str_offset = off;
      r.flat_offset += (int32_t)(row * stride_elems[(size_t)r.array_id]);
      records_acc.push_back(r);
    }
  }
  if ((int64_t)arena_acc.size() > arena_cap ||
      (int64_t)records_acc.size() > records_cap)
    return -2;
  // empty accumulators hand memcpy a null .data() — UB for a nonnull
  // parameter even at n=0 (no strings in the batch is a real case)
  if (!arena_acc.empty()) memcpy(arena, arena_acc.data(), arena_acc.size());
  if (!records_acc.empty())
    memcpy(records, records_acc.data(),
           records_acc.size() * sizeof(StringRecord));
  return (int64_t)records_acc.size();
}

}  // extern "C"
