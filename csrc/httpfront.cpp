// httpfront — GIL-free native HTTP/1.1 front-end for the policy server.
//
// The serving profile has been framing-bound since round 3: the in-process
// micro-batcher sustains 35-69k reviews/s while Python asyncio HTTP framing
// caps ≈1.3k requests/s per event loop (PROFILE.md rounds 3/5). This file is
// the csrc/ answer (fastenc.cpp / wasmint.cpp precedent): an epoll-based
// HTTP/1.1 server running entirely on native threads — accept, framing
// (keep-alive, chunked bodies, pipelining), AdmissionReview JSON parsing,
// and response serialization never touch the GIL. Python only drains parsed
// requests from a lock-free submission ring (one SPSC ring per event loop)
// and completes them through a lock-free MPSC completion stack.
//
// Parse fusion: the request handler parses the AdmissionReview ONCE,
// canonicalizing the `request` object into exactly the compact JSON bytes
// Python's json.dumps(AdmissionRequest.to_dict(), separators=(",", ":"))
// would produce (fixed key order, dropped nulls, normalized kind/resource,
// ensure_ascii escaping). Those bytes feed the fastenc native batch encoder
// directly (WireValidateRequest.payload_json()), so the old
// bytes→dict→re-serialize→encode double parse becomes one native pass.
// The canonicalizer is deliberately CONSERVATIVE: any construct whose
// Python-observable semantics it cannot reproduce byte-for-byte (floats,
// duplicate object keys, lone surrogates, non-string uid/namespace/
// operation, NaN/Infinity, depth > 96, invalid UTF-8, any syntax error)
// falls back to shipping the raw body for the Python parser — the Python
// frontend stays the correctness oracle, and 422 bodies are bit-exact by
// construction because Python renders them.
//
// Response serialization (round 19: batch-granular native response
// assembly): verdict shapes up to and including patches (patchType +
// base64 JSONPatch), warnings lists, and full status objects (message,
// code, reason, details.causes tables — group denials) serialize
// natively from packed v2 verdict records (parse_verdict_record) with
// json.dumps' default separators, byte-exact vs the Python responder
// (tests/test_native_assembly.py differential corpus; graftcheck RS01/
// RS02 pin the field classification and key order). Only
// auditAnnotations and non-encodable strings arrive pre-rendered from
// Python — the per-row oracle for hooks/mutations. HTTP response heads
// mirror aiohttp's (status line, Content-Type, Content-Length, Date,
// Server, Connection) so the differential framing corpus can require
// byte-parity modulo the Date value.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this environment).

#include <arpa/inet.h>
#include <dlfcn.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <stdio.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <array>
#include <atomic>
#include <cstdint>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

// ---------------------------------------------------------------- helpers --

inline int64_t now_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (int64_t)ts.tv_sec * 1000000000ll + ts.tv_nsec;
}

const char* reason_of(int status) {
  switch (status) {
    case 100: return "Continue";
    case 200: return "OK";
    case 202: return "Accepted";
    case 400: return "Bad Request";
    case 401: return "Unauthorized";
    case 403: return "Forbidden";
    case 404: return "Not Found";
    case 405: return "Method Not Allowed";
    case 409: return "Conflict";
    case 413: return "Request Entity Too Large";
    case 422: return "Unprocessable Entity";
    case 429: return "Too Many Requests";
    case 500: return "Internal Server Error";
    case 503: return "Service Unavailable";
    case 504: return "Gateway Timeout";
    default: return "Unknown";
  }
}

// ------------------------------------------------------- submission record --
// Wire layout of one parsed request handed to Python (little-endian):
//   u32 total_len (including this field)
//   u64 req_id
//   u8  kind      0=validate-parsed 1=audit-parsed 2=raw
//                 3=validate-fallback 4=audit-fallback
//   u8  flags     bit0: namespace present
//   u16 policy_len | u16 uid_len | u16 ns_len | u16 op_len | u16 gvk_len
//   u16 tp_len    (W3C traceparent header, verbatim; 0 when absent)
//   u32 payload_len
//   i64 t_first_ns | i64 t_parse_ns | i64 t_push_ns
//       flight-recorder stamps on CLOCK_MONOTONIC (the clock Python's
//       perf_counter_ns reads on Linux): request first byte observed,
//       request fully received (canonicalize begins), record pushed to
//       the ring. t_first is 0 when the request arrived in one read
//       (the arrival window never opened).
//   bytes: policy_id, uid, namespace, operation, requestKind.kind,
//          traceparent, payload
// Parsed kinds carry the canonical payload; raw/fallback carry the raw body.

constexpr int K_VALIDATE = 0, K_AUDIT = 1, K_RAW = 2, K_VALIDATE_FB = 3,
              K_AUDIT_FB = 4;

// graftcheck: abi(policy_server_tpu/runtime/native_frontend.py:_REC)
struct RecHeader {
  uint32_t total_len;
  uint64_t req_id;
  uint8_t kind;
  uint8_t flags;
  uint16_t policy_len, uid_len, ns_len, op_len, gvk_len, tp_len;
  uint32_t payload_len;
  int64_t t_first_ns, t_parse_ns, t_push_ns;
} __attribute__((packed));

// graftcheck: wire-input
uint8_t* build_record(uint64_t req_id, int kind, bool has_ns,
                      const std::string& policy, const std::string& uid,
                      const std::string& ns, const std::string& op,
                      const std::string& gvk, const std::string& tp,
                      const std::string& payload, int64_t t_first,
                      int64_t t_parse, int64_t t_push) {
  // every wire-length field is narrower than size_t: a field that does
  // not fit its slot must fail the record, never truncate into a header
  // whose lens disagree with the bytes that follow (the Python drainer
  // would mis-split the record body). submit_request bounds the canon
  // fields and routing bounds policy_id, so this rejects nothing in
  // practice — it exists so the wire format cannot be corrupted by a
  // future caller that forgets.
  if (policy.size() > 0xFFFF || uid.size() > 0xFFFF || ns.size() > 0xFFFF ||
      op.size() > 0xFFFF || gvk.size() > 0xFFFF || tp.size() > 0xFFFF ||
      payload.size() > 0xFFFFFFFFull)
    return nullptr;
  size_t total = sizeof(RecHeader) + policy.size() + uid.size() + ns.size() +
                 op.size() + gvk.size() + tp.size() + payload.size();
  uint8_t* blob = (uint8_t*)malloc(total);
  RecHeader h;
  h.total_len = (uint32_t)total;
  h.req_id = req_id;
  h.kind = (uint8_t)kind;
  h.flags = has_ns ? 1 : 0;
  h.policy_len = (uint16_t)policy.size();
  h.uid_len = (uint16_t)uid.size();
  h.ns_len = (uint16_t)ns.size();
  h.op_len = (uint16_t)op.size();
  h.gvk_len = (uint16_t)gvk.size();
  h.tp_len = (uint16_t)tp.size();
  h.payload_len = (uint32_t)payload.size();
  h.t_first_ns = t_first;
  h.t_parse_ns = t_parse;
  h.t_push_ns = t_push;
  uint8_t* p = blob;
  memcpy(p, &h, sizeof(h)); p += sizeof(h);
  memcpy(p, policy.data(), policy.size()); p += policy.size();
  memcpy(p, uid.data(), uid.size()); p += uid.size();
  memcpy(p, ns.data(), ns.size()); p += ns.size();
  memcpy(p, op.data(), op.size()); p += op.size();
  memcpy(p, gvk.data(), gvk.size()); p += gvk.size();
  memcpy(p, tp.data(), tp.size()); p += tp.size();
  memcpy(p, payload.data(), payload.size());
  return blob;
}

// ------------------------------------------------- lock-free SPSC sub ring --
// One producer (the owning event-loop thread), one consumer (the Python
// drainer). Slots hold malloc'd record blobs; capacity is a power of two.

struct SubRing {
  std::vector<std::atomic<uint8_t*>> slots;
  size_t mask;
  std::atomic<uint64_t> head{0};  // producer: next write index
  std::atomic<uint64_t> tail{0};  // consumer: next read index

  explicit SubRing(size_t bits) : slots(1ull << bits), mask((1ull << bits) - 1) {
    for (auto& s : slots) s.store(nullptr, std::memory_order_relaxed);
  }
  // Returns -1 when full, 1 when pushed onto an EMPTY ring (the consumer
  // may be blocked — wake it), 0 when pushed behind existing records
  // (the consumer re-scans before blocking, so no wake syscall needed —
  // syscalls are ~10-25us on sandboxed kernels and dominate at rate).
  int push(uint8_t* rec) {
    uint64_t h = head.load(std::memory_order_relaxed);
    uint64_t t = tail.load(std::memory_order_acquire);
    if (h - t > mask) return -1;  // full
    slots[h & mask].store(rec, std::memory_order_relaxed);
    head.store(h + 1, std::memory_order_release);
    return h == t ? 1 : 0;
  }
  uint8_t* pop() {
    uint64_t t = tail.load(std::memory_order_relaxed);
    if (t == head.load(std::memory_order_acquire)) return nullptr;
    uint8_t* rec = slots[t & mask].load(std::memory_order_relaxed);
    tail.store(t + 1, std::memory_order_release);
    return rec;
  }
  // consumer-side peek/advance pair: the drainer must see a record's size
  // BEFORE committing to copy it into the (bounded) poll buffer
  uint8_t* peek() {
    uint64_t t = tail.load(std::memory_order_relaxed);
    if (t == head.load(std::memory_order_acquire)) return nullptr;
    return slots[t & mask].load(std::memory_order_relaxed);
  }
  void advance() {
    tail.store(tail.load(std::memory_order_relaxed) + 1,
               std::memory_order_release);
  }
};

// --------------------------------------------- lock-free MPSC completions --
// Producers: arbitrary Python threads (batcher pool workers, the drainer).
// Consumer: the owning event-loop thread. Classic Treiber stack; the
// consumer takes the whole stack with one exchange and reverses it so
// responses complete in push order.

struct Comp {
  Comp* next;
  uint64_t req_id;
  int status;
  int retry_after;  // <=0: none
  std::string body;
};

struct CompStack {
  std::atomic<Comp*> top{nullptr};
  // true when pushed onto an EMPTY stack: the first pusher after a
  // consumer drain issues the (expensive) eventfd wake; later pushers
  // coalesce onto the already-pending wakeup
  bool push(Comp* c) {
    Comp* t = top.load(std::memory_order_relaxed);
    do {
      c->next = t;
    } while (!top.compare_exchange_weak(t, c, std::memory_order_release,
                                        std::memory_order_relaxed));
    return t == nullptr;
  }
  Comp* take_all_reversed() {
    Comp* c = top.exchange(nullptr, std::memory_order_acquire);
    Comp* rev = nullptr;
    while (c) {
      Comp* nx = c->next;
      c->next = rev;
      rev = c;
      c = nx;
    }
    return rev;
  }
};

// ----------------------------------------------------- JSON canonicalizer --
// Strict parser + writer reproducing Python json.dumps byte-for-byte for
// the subset it accepts; anything else returns false → Python fallback.

constexpr int MAX_DEPTH = 96;

struct Jp {
  const char* p;
  const char* end;
  void ws() {
    while (p < end &&
           (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      p++;
  }
  bool lit(const char* s, size_t n) {
    if ((size_t)(end - p) < n || memcmp(p, s, n) != 0) return false;
    p += n;
    return true;
  }
};

// graftcheck: wire-input
bool valid_utf8(const uint8_t* s, size_t n) {
  size_t i = 0;
  while (i < n) {
    uint8_t c = s[i];
    if (c < 0x80) { i++; continue; }
    int len;
    uint32_t cp;
    if ((c & 0xE0) == 0xC0) { len = 2; cp = c & 0x1F; }
    else if ((c & 0xF0) == 0xE0) { len = 3; cp = c & 0x0F; }
    else if ((c & 0xF8) == 0xF0) { len = 4; cp = c & 0x07; }
    else return false;
    if (i + len > n) return false;
    for (int k = 1; k < len; k++) {
      if ((s[i + k] & 0xC0) != 0x80) return false;
      cp = (cp << 6) | (s[i + k] & 0x3F);
    }
    if (len == 2 && cp < 0x80) return false;          // overlong
    if (len == 3 && cp < 0x800) return false;
    if (len == 4 && cp < 0x10000) return false;
    if (cp > 0x10FFFF) return false;
    if (cp >= 0xD800 && cp <= 0xDFFF) return false;   // raw surrogate
    i += len;
  }
  return true;
}

// Decode a JSON string literal (at *p == '"') into UTF-8 `out`. Rejects
// lone surrogates and invalid escapes (Python tolerates lone surrogates;
// re-emitting them byte-exactly needs surrogate bookkeeping we skip —
// fallback is correct, just slower).
// graftcheck: wire-input
bool jstr(Jp& ps, std::string& out) {
  if (ps.p >= ps.end || *ps.p != '"') return false;
  ps.p++;
  while (ps.p < ps.end) {
    unsigned char c = (unsigned char)*ps.p;
    if (c == '"') { ps.p++; return true; }
    if (c < 0x20) return false;  // raw control char: Python rejects too
    if (c == '\\') {
      ps.p++;
      if (ps.p >= ps.end) return false;
      char e = *ps.p++;
      switch (e) {
        case '"': out.push_back('"'); break;
        case '\\': out.push_back('\\'); break;
        case '/': out.push_back('/'); break;
        case 'b': out.push_back('\b'); break;
        case 'f': out.push_back('\f'); break;
        case 'n': out.push_back('\n'); break;
        case 'r': out.push_back('\r'); break;
        case 't': out.push_back('\t'); break;
        case 'u': {
          if (ps.end - ps.p < 4) return false;
          uint32_t cp = 0;
          for (int i = 0; i < 4; i++) {
            char h = ps.p[i];
            cp <<= 4;
            if (h >= '0' && h <= '9') cp |= h - '0';
            else if (h >= 'a' && h <= 'f') cp |= h - 'a' + 10;
            else if (h >= 'A' && h <= 'F') cp |= h - 'A' + 10;
            else return false;
          }
          ps.p += 4;
          if (cp >= 0xD800 && cp <= 0xDBFF) {
            if (ps.end - ps.p < 6 || ps.p[0] != '\\' || ps.p[1] != 'u')
              return false;  // lone high surrogate
            uint32_t lo = 0;
            for (int i = 0; i < 4; i++) {
              char h = ps.p[2 + i];
              lo <<= 4;
              if (h >= '0' && h <= '9') lo |= h - '0';
              else if (h >= 'a' && h <= 'f') lo |= h - 'a' + 10;
              else if (h >= 'A' && h <= 'F') lo |= h - 'A' + 10;
              else return false;
            }
            if (lo < 0xDC00 || lo > 0xDFFF) return false;
            ps.p += 6;
            cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
          } else if (cp >= 0xDC00 && cp <= 0xDFFF) {
            return false;  // lone low surrogate
          }
          if (cp < 0x80) out.push_back((char)cp);
          else if (cp < 0x800) {
            out.push_back((char)(0xC0 | (cp >> 6)));
            out.push_back((char)(0x80 | (cp & 0x3F)));
          } else if (cp < 0x10000) {
            out.push_back((char)(0xE0 | (cp >> 12)));
            out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
            out.push_back((char)(0x80 | (cp & 0x3F)));
          } else {
            out.push_back((char)(0xF0 | (cp >> 18)));
            out.push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
            out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
            out.push_back((char)(0x80 | (cp & 0x3F)));
          }
          break;
        }
        default: return false;
      }
    } else {
      out.push_back((char)c);
      ps.p++;
    }
  }
  return false;
}

// Emit a UTF-8 string as Python json.dumps would (ensure_ascii=True):
// every char outside 0x20..0x7E escaped, lowercase hex, surrogate pairs
// for astral code points. Input must be valid UTF-8 (caller checked).
void py_escape(const std::string& s, std::string& out) {
  static const char* hexd = "0123456789abcdef";
  out.push_back('"');
  size_t i = 0, n = s.size();
  const uint8_t* d = (const uint8_t*)s.data();
  auto esc = [&](uint32_t u) {
    out.push_back('\\');
    out.push_back('u');
    out.push_back(hexd[(u >> 12) & 0xF]);
    out.push_back(hexd[(u >> 8) & 0xF]);
    out.push_back(hexd[(u >> 4) & 0xF]);
    out.push_back(hexd[u & 0xF]);
  };
  while (i < n) {
    uint8_t c = d[i];
    if (c < 0x80) {
      switch (c) {
        case '"': out += "\\\""; break;
        case '\\': out += "\\\\"; break;
        case '\b': out += "\\b"; break;
        case '\f': out += "\\f"; break;
        case '\n': out += "\\n"; break;
        case '\r': out += "\\r"; break;
        case '\t': out += "\\t"; break;
        default:
          if (c < 0x20 || c == 0x7F) esc(c);
          else out.push_back((char)c);
      }
      i++;
      continue;
    }
    uint32_t cp;
    int len;
    if ((c & 0xE0) == 0xC0) { len = 2; cp = c & 0x1F; }
    else if ((c & 0xF0) == 0xE0) { len = 3; cp = c & 0x0F; }
    else { len = 4; cp = c & 0x07; }
    // verdict-record fields reach here unvalidated (the Python packer
    // is the trusted producer, but httpfront_render_verdict is exported
    // for arbitrary bytes): a multibyte lead truncated by the end of
    // the field must not read past it — clamp and escape the garbage
    if (i + (size_t)len > n) len = (int)(n - i);
    for (int k = 1; k < len; k++) cp = (cp << 6) | (d[i + k] & 0x3F);
    i += len;
    if (cp < 0x10000) {
      esc(cp);
    } else {
      cp -= 0x10000;
      esc(0xD800 + (cp >> 10));
      esc(0xDC00 + (cp & 0x3FF));
    }
  }
  out.push_back('"');
}

// Strict number: -?(0|[1-9][0-9]*)(\.[0-9]+)?([eE][+-]?[0-9]+)?
// Integers are re-emittable verbatim; fractions/exponents (Python float
// repr) and "-0" (Python normalizes to 0) are not → is_int=false.
bool jnum(Jp& ps, const char** start, const char** stop, bool* is_int) {
  const char* p = ps.p;
  const char* end = ps.end;
  *start = p;
  bool neg = false;
  if (p < end && *p == '-') { neg = true; p++; }
  if (p >= end) return false;
  if (*p == '0') {
    p++;
  } else if (*p >= '1' && *p <= '9') {
    while (p < end && *p >= '0' && *p <= '9') p++;
  } else {
    return false;
  }
  bool integral = true;
  if (p < end && *p == '.') {
    integral = false;
    p++;
    if (p >= end || *p < '0' || *p > '9') return false;
    while (p < end && *p >= '0' && *p <= '9') p++;
  }
  if (p < end && (*p == 'e' || *p == 'E')) {
    integral = false;
    p++;
    if (p < end && (*p == '+' || *p == '-')) p++;
    if (p >= end || *p < '0' || *p > '9') return false;
    while (p < end && *p >= '0' && *p <= '9') p++;
  }
  // "-0" loads as int 0 in Python; verbatim re-emit would diverge
  if (neg && integral && (p - *start) == 2 && (*start)[1] == '0')
    integral = false;
  *stop = p;
  *is_int = integral;
  ps.p = p;
  return true;
}

// Canonicalize one JSON value: parse strictly, append the exact bytes
// Python json.dumps(value, separators=(",",":")) would produce. Objects
// keep wire key order (Python dicts preserve insertion); duplicate keys,
// floats, and anything surrogate-y bail out.
bool canon_value(Jp& ps, std::string& out, int depth) {
  if (depth > MAX_DEPTH) return false;
  ps.ws();
  if (ps.p >= ps.end) return false;
  char c = *ps.p;
  if (c == '"') {
    std::string s;
    if (!jstr(ps, s)) return false;
    py_escape(s, out);
    return true;
  }
  if (c == 't') { if (!ps.lit("true", 4)) return false; out += "true"; return true; }
  if (c == 'f') { if (!ps.lit("false", 5)) return false; out += "false"; return true; }
  if (c == 'n') { if (!ps.lit("null", 4)) return false; out += "null"; return true; }
  if (c == '{') {
    ps.p++;
    ps.ws();
    out.push_back('{');
    if (ps.p < ps.end && *ps.p == '}') { ps.p++; out.push_back('}'); return true; }
    std::unordered_set<std::string> seen;
    bool first = true;
    while (ps.p < ps.end) {
      ps.ws();
      std::string key;
      if (!jstr(ps, key)) return false;
      if (!seen.insert(key).second) return false;  // dup: Python last-wins
      ps.ws();
      if (ps.p >= ps.end || *ps.p != ':') return false;
      ps.p++;
      if (!first) out.push_back(',');
      first = false;
      py_escape(key, out);
      out.push_back(':');
      if (!canon_value(ps, out, depth + 1)) return false;
      ps.ws();
      if (ps.p < ps.end && *ps.p == ',') { ps.p++; continue; }
      if (ps.p < ps.end && *ps.p == '}') { ps.p++; out.push_back('}'); return true; }
      return false;
    }
    return false;
  }
  if (c == '[') {
    ps.p++;
    ps.ws();
    out.push_back('[');
    if (ps.p < ps.end && *ps.p == ']') { ps.p++; out.push_back(']'); return true; }
    bool first = true;
    while (ps.p < ps.end) {
      if (!first) out.push_back(',');
      first = false;
      if (!canon_value(ps, out, depth + 1)) return false;
      ps.ws();
      if (ps.p < ps.end && *ps.p == ',') { ps.p++; continue; }
      if (ps.p < ps.end && *ps.p == ']') { ps.p++; out.push_back(']'); return true; }
      return false;
    }
    return false;
  }
  const char *s0, *s1;
  bool is_int;
  if (!jnum(ps, &s0, &s1, &is_int)) return false;
  if (!is_int) return false;  // float repr parity is Python's job
  out.append(s0, (size_t)(s1 - s0));
  return true;
}

// Validate-and-skip one JSON value (content is dropped; syntax must still
// be at-least-as-strict as Python so "native 200 / python 422" divergence
// cannot happen). Floats ARE fine here — skipped values are never
// re-emitted.
bool skip_value(Jp& ps, int depth) {
  if (depth > MAX_DEPTH) return false;
  ps.ws();
  if (ps.p >= ps.end) return false;
  char c = *ps.p;
  if (c == '"') { std::string s; return jstr(ps, s); }
  if (c == 't') return ps.lit("true", 4);
  if (c == 'f') return ps.lit("false", 5);
  if (c == 'n') return ps.lit("null", 4);
  if (c == '{') {
    ps.p++;
    ps.ws();
    if (ps.p < ps.end && *ps.p == '}') { ps.p++; return true; }
    while (ps.p < ps.end) {
      ps.ws();
      std::string key;
      if (!jstr(ps, key)) return false;
      ps.ws();
      if (ps.p >= ps.end || *ps.p != ':') return false;
      ps.p++;
      if (!skip_value(ps, depth + 1)) return false;
      ps.ws();
      if (ps.p < ps.end && *ps.p == ',') { ps.p++; continue; }
      if (ps.p < ps.end && *ps.p == '}') { ps.p++; return true; }
      return false;
    }
    return false;
  }
  if (c == '[') {
    ps.p++;
    ps.ws();
    if (ps.p < ps.end && *ps.p == ']') { ps.p++; return true; }
    while (ps.p < ps.end) {
      if (!skip_value(ps, depth + 1)) return false;
      ps.ws();
      if (ps.p < ps.end && *ps.p == ',') { ps.p++; continue; }
      if (ps.p < ps.end && *ps.p == ']') { ps.p++; return true; }
      return false;
    }
    return false;
  }
  const char *s0, *s1;
  bool is_int;
  return jnum(ps, &s0, &s1, &is_int);
}

struct Span {
  const char* a = nullptr;
  const char* b = nullptr;
  bool present() const { return a != nullptr; }
};

bool span_is_null(const Span& s) {
  Jp ps{s.a, s.b};
  ps.ws();
  return ps.lit("null", 4);
}

// A span that must hold a JSON string → decoded value.
bool span_string(const Span& s, std::string& out) {
  Jp ps{s.a, s.b};
  ps.ws();
  if (ps.p >= ps.end || *ps.p != '"') return false;
  return jstr(ps, out);
}

// Normalize a kind/resource sub-object per GroupVersionKind.from_dict:
// {"group": g, "version": v, "kind"/"resource": k} with "" for missing or
// null; values must be JSON strings (non-string truthiness games →
// fallback); unknown sub-keys ignored; duplicate known sub-keys bail.
bool canon_gvk(const Span& s, const char* third_key, std::string& out,
               std::string* kind_out) {
  std::string g, v, k;
  bool has_g = false, has_v = false, has_k = false;
  if (s.present() && !span_is_null(s)) {
    Jp ps{s.a, s.b};
    ps.ws();
    if (ps.p >= ps.end || *ps.p != '{') return false;
    ps.p++;
    ps.ws();
    if (ps.p < ps.end && *ps.p == '}') {
      ps.p++;
    } else {
      while (ps.p < ps.end) {
        ps.ws();
        std::string key;
        if (!jstr(ps, key)) return false;
        ps.ws();
        if (ps.p >= ps.end || *ps.p != ':') return false;
        ps.p++;
        ps.ws();
        bool known = key == "group" || key == "version" || key == third_key;
        if (known) {
          std::string* dst = key == "group" ? &g
                             : key == "version" ? &v : &k;
          bool* flag = key == "group" ? &has_g
                       : key == "version" ? &has_v : &has_k;
          if (*flag) return false;  // dup
          *flag = true;
          if (ps.p < ps.end && *ps.p == 'n') {
            if (!ps.lit("null", 4)) return false;  // null → ""
          } else if (!jstr(ps, *dst)) {
            return false;  // non-string value: truthiness games → Python
          }
        } else {
          if (!skip_value(ps, 0)) return false;
        }
        ps.ws();
        if (ps.p < ps.end && *ps.p == ',') { ps.p++; continue; }
        if (ps.p < ps.end && *ps.p == '}') { ps.p++; break; }
        return false;
      }
    }
    Jp tail = ps;
    tail.ws();
    if (tail.p != tail.end) return false;
  }
  out += "{\"group\":";
  py_escape(g, out);
  out += ",\"version\":";
  py_escape(v, out);
  out += ",\"";
  out += third_key;
  out += "\":";
  py_escape(k, out);
  out.push_back('}');
  if (kind_out) *kind_out = k;
  return true;
}

struct CanonResult {
  std::string uid, ns, op, gvk;  // gvk = requestKind.kind ("" when absent)
  bool has_ns = false;
  std::string payload;           // canonical compact request JSON
};

// Canonicalize a full AdmissionReview body → CanonResult. Returns false
// for ANYTHING it cannot reproduce byte-exactly → Python fallback.
bool canon_admission_review(const char* body, size_t len, CanonResult& out) {
  if (!valid_utf8((const uint8_t*)body, len)) return false;
  Jp ps{body, body + len};
  ps.ws();
  if (ps.p >= ps.end || *ps.p != '{') return false;
  ps.p++;
  ps.ws();
  Span request;
  if (ps.p < ps.end && *ps.p == '}') {
    ps.p++;
  } else {
    while (ps.p < ps.end) {
      ps.ws();
      std::string key;
      if (!jstr(ps, key)) return false;
      ps.ws();
      if (ps.p >= ps.end || *ps.p != ':') return false;
      ps.p++;
      ps.ws();
      if (key == "request") {
        if (request.present()) return false;  // dup request key
        request.a = ps.p;
        if (!skip_value(ps, 0)) return false;
        request.b = ps.p;
      } else {
        if (!skip_value(ps, 0)) return false;
      }
      ps.ws();
      if (ps.p < ps.end && *ps.p == ',') { ps.p++; continue; }
      if (ps.p < ps.end && *ps.p == '}') { ps.p++; break; }
      return false;
    }
  }
  ps.ws();
  if (ps.p != ps.end) return false;  // trailing garbage: Python 422s
  if (!request.present()) return false;  // missing request: Python 422s

  // second pass: collect the known request fields' spans
  Jp rq{request.a, request.b};
  rq.ws();
  if (rq.p >= rq.end || *rq.p != '{') return false;
  rq.p++;
  rq.ws();
  Span f_uid, f_kind, f_resource, f_sub, f_rkind, f_rres, f_rsub, f_name,
      f_ns, f_op, f_user, f_obj, f_old, f_dry, f_opt;
  struct KV { const char* name; Span* span; };
  const KV table[] = {
      {"uid", &f_uid}, {"kind", &f_kind}, {"resource", &f_resource},
      {"subResource", &f_sub}, {"requestKind", &f_rkind},
      {"requestResource", &f_rres}, {"requestSubResource", &f_rsub},
      {"name", &f_name}, {"namespace", &f_ns}, {"operation", &f_op},
      {"userInfo", &f_user}, {"object", &f_obj}, {"oldObject", &f_old},
      {"dryRun", &f_dry}, {"options", &f_opt},
  };
  if (rq.p < rq.end && *rq.p == '}') {
    rq.p++;
  } else {
    while (rq.p < rq.end) {
      rq.ws();
      std::string key;
      if (!jstr(rq, key)) return false;
      rq.ws();
      if (rq.p >= rq.end || *rq.p != ':') return false;
      rq.p++;
      rq.ws();
      Span* dst = nullptr;
      for (const auto& kv : table)
        if (key == kv.name) { dst = kv.span; break; }
      const char* a = rq.p;
      if (!skip_value(rq, 0)) return false;
      if (dst != nullptr) {
        if (dst->present()) return false;  // dup known key
        dst->a = a;
        dst->b = rq.p;
      }
      rq.ws();
      if (rq.p < rq.end && *rq.p == ',') { rq.p++; continue; }
      if (rq.p < rq.end && *rq.p == '}') { rq.p++; break; }
      return false;
    }
  }
  Jp rtail = rq;
  rtail.ws();
  if (rtail.p != rtail.end) return false;

  // uid: required non-empty string (else Python raises the exact 422)
  if (!f_uid.present() || !span_string(f_uid, out.uid) || out.uid.empty())
    return false;

  std::string& pl = out.payload;
  pl.reserve((size_t)(request.b - request.a) + 64);
  pl += "{\"uid\":";
  py_escape(out.uid, pl);
  pl += ",\"kind\":";
  if (!canon_gvk(f_kind, "kind", pl, nullptr)) return false;
  pl += ",\"resource\":";
  if (!canon_gvk(f_resource, "resource", pl, nullptr)) return false;

  auto emit_optional = [&](const Span& s, const char* key) -> bool {
    if (!s.present() || span_is_null(s)) return true;
    pl += ",\"";
    pl += key;
    pl += "\":";
    Jp vp{s.a, s.b};
    if (!canon_value(vp, pl, 0)) return false;
    Jp vt = vp;
    vt.ws();
    return vt.p == vt.end;
  };

  if (!emit_optional(f_sub, "subResource")) return false;
  if (f_rkind.present() && !span_is_null(f_rkind)) {
    pl += ",\"requestKind\":";
    if (!canon_gvk(f_rkind, "kind", pl, &out.gvk)) return false;
  }
  if (f_rres.present() && !span_is_null(f_rres)) {
    pl += ",\"requestResource\":";
    if (!canon_gvk(f_rres, "resource", pl, nullptr)) return false;
  }
  if (!emit_optional(f_rsub, "requestSubResource")) return false;
  if (!emit_optional(f_name, "name")) return false;
  // namespace: header consumers (always-accept shortcut, metric labels)
  // read it as a string — require string-or-absent
  if (f_ns.present() && !span_is_null(f_ns)) {
    if (!span_string(f_ns, out.ns)) return false;
    out.has_ns = true;
    pl += ",\"namespace\":";
    py_escape(out.ns, pl);
  }
  // operation: `d.get("operation", "") or ""` — falsy → ""; require
  // string-or-absent-or-null (0/false → Python)
  if (f_op.present() && !span_is_null(f_op)) {
    if (!span_string(f_op, out.op)) return false;
  }
  pl += ",\"operation\":";
  py_escape(out.op, pl);
  // userInfo: dict(x or {}) then `or None` — {} and [] drop, object
  // emits in wire order, anything else → Python
  if (f_user.present() && !span_is_null(f_user)) {
    Jp up{f_user.a, f_user.b};
    up.ws();
    if (up.p < up.end && *up.p == '{') {
      std::string tmp;
      Jp vp{f_user.a, f_user.b};
      if (!canon_value(vp, tmp, 0)) return false;
      Jp vt = vp;
      vt.ws();
      if (vt.p != vt.end) return false;
      if (tmp != "{}") {
        pl += ",\"userInfo\":";
        pl += tmp;
      }
    } else if (up.p < up.end && *up.p == '[') {
      Jp vp = up;
      if (!skip_value(vp, 0)) return false;
      std::string probe(up.p, (size_t)(f_user.b - up.p));
      // only the empty array maps to dict([]) == {} → dropped
      Jp ep{f_user.a, f_user.b};
      ep.ws();
      ep.p++;
      ep.ws();
      if (ep.p >= ep.end || *ep.p != ']') return false;
    } else {
      return false;
    }
  }
  if (!emit_optional(f_obj, "object")) return false;
  if (!emit_optional(f_old, "oldObject")) return false;
  if (!emit_optional(f_dry, "dryRun")) return false;
  if (!emit_optional(f_opt, "options")) return false;
  pl.push_back('}');
  return true;
}

// --------------------------------------------------------------- responses --

struct StaticResp {
  int status = 0;
  std::string content_type;
  std::string body;         // 413 slot: printf template with one %lld
  std::string extra;        // extra header lines, e.g. "Allow: POST\r\n"
};

enum { ST_404 = 0, ST_405 = 1, ST_413 = 2, ST_503 = 3, ST_400 = 4, ST_MAX = 5 };

// -------------------------------------------------------------------- tls --
//
// Native TLS termination (round 20): OpenSSL is bound at RUNTIME via
// dlopen — this toolchain ships libssl.so.1.1/libcrypto.so.1.1 but no
// development headers, so the needed subset of the OpenSSL 1.1 API is
// declared here (the 1.1 ABI is stable; the same names resolve against
// 3.x). A missing or incomplete libssl leaves TlsApi::ok false and
// httpfront_tls_available() reports it, so the Python side degrades
// LOUDLY to the aiohttp TLS frontend (round-11 fallback precedent)
// instead of silently serving plaintext.
//
// Handshakes run on memory BIOs entirely inside the event loop: the
// socket stays in the same non-blocking epoll state machine, raw bytes
// are pumped socket→rbio and wbio→socket, and SSL_read/SSL_write sit
// between the socket and the UNCHANGED plaintext parser/assembler.
// kTLS offload after the userspace handshake needs OpenSSL 3.x
// (SSL_OP_ENABLE_KTLS); against 1.1 the capability probe answers no
// and the Python side logs it — a probe, never a silent downgrade.

constexpr int kSSL_ERROR_WANT_READ = 2;
constexpr int kSSL_ERROR_WANT_WRITE = 3;
constexpr int kSSL_ERROR_ZERO_RETURN = 6;
constexpr int kSSL_CTRL_SET_MIN_PROTO_VERSION = 123;
constexpr int kSSL_CTRL_EXTRA_CHAIN_CERT = 14;
constexpr long kTLS1_2_VERSION = 0x0303;
constexpr int kSSL_VERIFY_PEER = 0x01;
constexpr int kSSL_VERIFY_FAIL_IF_NO_PEER_CERT = 0x02;

struct TlsApi {
  bool ok = false;
  bool ktls = false;  // SSL_sendfile present (OpenSSL 3.x kTLS build)
  std::string err;    // why the binding is unavailable
  // libcrypto
  void* (*BIO_new)(const void*) = nullptr;
  const void* (*BIO_s_mem)() = nullptr;
  int (*BIO_write)(void*, const void*, int) = nullptr;
  int (*BIO_read)(void*, void*, int) = nullptr;
  size_t (*BIO_ctrl_pending)(void*) = nullptr;
  void* (*BIO_new_mem_buf)(const void*, int) = nullptr;
  int (*BIO_free)(void*) = nullptr;
  void* (*PEM_read_bio_X509)(void*, void*, void*, void*) = nullptr;
  void* (*PEM_read_bio_PrivateKey)(void*, void*, void*, void*) = nullptr;
  int (*X509_STORE_add_cert)(void*, void*) = nullptr;
  void (*X509_free)(void*) = nullptr;
  void (*EVP_PKEY_free)(void*) = nullptr;
  void (*ERR_clear_error)() = nullptr;
  unsigned long (*ERR_get_error)() = nullptr;
  void (*ERR_error_string_n)(unsigned long, char*, size_t) = nullptr;
  // libssl
  const void* (*TLS_server_method)() = nullptr;
  void* (*SSL_CTX_new)(const void*) = nullptr;
  void (*SSL_CTX_free)(void*) = nullptr;
  long (*SSL_CTX_ctrl)(void*, int, long, void*) = nullptr;
  int (*SSL_CTX_use_certificate)(void*, void*) = nullptr;
  int (*SSL_CTX_use_PrivateKey)(void*, void*) = nullptr;
  int (*SSL_CTX_check_private_key)(const void*) = nullptr;
  void (*SSL_CTX_set_verify)(void*, int, void*) = nullptr;
  void* (*SSL_CTX_get_cert_store)(const void*) = nullptr;
  void* (*SSL_new)(void*) = nullptr;
  void (*SSL_free)(void*) = nullptr;
  void (*SSL_set_bio)(void*, void*, void*) = nullptr;
  void (*SSL_set_accept_state)(void*) = nullptr;
  int (*SSL_do_handshake)(void*) = nullptr;
  int (*SSL_read)(void*, void*, int) = nullptr;
  int (*SSL_write)(void*, const void*, int) = nullptr;
  int (*SSL_get_error)(const void*, int) = nullptr;
  int (*SSL_shutdown)(void*) = nullptr;
};

TlsApi* tls_api() {
  static TlsApi* api = [] {
    TlsApi* a = new TlsApi();
    // matched pairs only: a 3.x libssl over a 1.1 libcrypto (or the
    // reverse) resolves symbols but corrupts state
    const char* pairs[][2] = {{"libssl.so.3", "libcrypto.so.3"},
                              {"libssl.so.1.1", "libcrypto.so.1.1"},
                              {"libssl.so", "libcrypto.so"}};
    void* hs = nullptr;
    void* hc = nullptr;
    for (auto& p : pairs) {
      hc = dlopen(p[1], RTLD_NOW | RTLD_GLOBAL);
      if (hc == nullptr) continue;
      hs = dlopen(p[0], RTLD_NOW | RTLD_GLOBAL);
      if (hs != nullptr) break;
    }
    if (hs == nullptr || hc == nullptr) {
      a->err = "libssl/libcrypto not found (tried .so.3, .so.1.1, .so)";
      return a;
    }
    const char* missing = nullptr;
    auto need = [&](void* h, const char* name) -> void* {
      void* p = dlsym(h, name);
      if (p == nullptr && missing == nullptr) missing = name;
      return p;
    };
#define TLS_SYM(handle, name) \
  a->name = reinterpret_cast<decltype(a->name)>(need(handle, #name))
    TLS_SYM(hc, BIO_new);
    TLS_SYM(hc, BIO_s_mem);
    TLS_SYM(hc, BIO_write);
    TLS_SYM(hc, BIO_read);
    TLS_SYM(hc, BIO_ctrl_pending);
    TLS_SYM(hc, BIO_new_mem_buf);
    TLS_SYM(hc, BIO_free);
    TLS_SYM(hc, PEM_read_bio_X509);
    TLS_SYM(hc, PEM_read_bio_PrivateKey);
    TLS_SYM(hc, X509_STORE_add_cert);
    TLS_SYM(hc, X509_free);
    TLS_SYM(hc, EVP_PKEY_free);
    TLS_SYM(hc, ERR_clear_error);
    TLS_SYM(hc, ERR_get_error);
    TLS_SYM(hc, ERR_error_string_n);
    TLS_SYM(hs, TLS_server_method);
    TLS_SYM(hs, SSL_CTX_new);
    TLS_SYM(hs, SSL_CTX_free);
    TLS_SYM(hs, SSL_CTX_ctrl);
    TLS_SYM(hs, SSL_CTX_use_certificate);
    TLS_SYM(hs, SSL_CTX_use_PrivateKey);
    TLS_SYM(hs, SSL_CTX_check_private_key);
    TLS_SYM(hs, SSL_CTX_set_verify);
    TLS_SYM(hs, SSL_CTX_get_cert_store);
    TLS_SYM(hs, SSL_new);
    TLS_SYM(hs, SSL_free);
    TLS_SYM(hs, SSL_set_bio);
    TLS_SYM(hs, SSL_set_accept_state);
    TLS_SYM(hs, SSL_do_handshake);
    TLS_SYM(hs, SSL_read);
    TLS_SYM(hs, SSL_write);
    TLS_SYM(hs, SSL_get_error);
    TLS_SYM(hs, SSL_shutdown);
#undef TLS_SYM
    if (missing != nullptr) {
      a->err = std::string("libssl symbol missing: ") + missing;
      return a;
    }
    a->ktls = dlsym(hs, "SSL_sendfile") != nullptr;
    a->ok = true;
    return a;
  }();
  return api;
}

thread_local char g_tls_err[256] = {0};

void set_tls_err(const char* what) {
  TlsApi* a = tls_api();
  unsigned long e = a->ok ? a->ERR_get_error() : 0;
  if (e != 0) {
    char ebuf[160];
    a->ERR_error_string_n(e, ebuf, sizeof(ebuf));
    snprintf(g_tls_err, sizeof(g_tls_err), "%s: %s", what, ebuf);
    while (a->ERR_get_error() != 0) {  // drain the queue for next time
    }
  } else {
    snprintf(g_tls_err, sizeof(g_tls_err), "%s", what);
  }
}

// One SSL_CTX "generation". Hot rotation swaps the Front's current
// generation under a mutex taken only at accept/swap time; every live
// connection pins the generation it handshook under via a refcount, so
// established connections DRAIN on the old identity while new accepts
// see the new one — exactly certs.py's SNI-callback contract.
struct TlsCtx {
  void* ctx = nullptr;  // SSL_CTX*
  std::atomic<long> refs{1};
};

void tls_ctx_unref(TlsCtx* t) {
  if (t != nullptr && t->refs.fetch_add(-1, std::memory_order_acq_rel) == 1) {
    tls_api()->SSL_CTX_free(t->ctx);
    delete t;
  }
}

// ------------------------------------------------------------------- conn --

struct PendingResp {
  uint64_t id;
  bool done = false;
  bool close_after = false;
  bool http10 = false;  // captured at parse time: the conn's per-request
                        // state resets before the completion arrives
  std::string wire;     // full head+body, ready to write
};

struct Conn {
  int fd;
  std::string in;
  size_t off = 0;  // parse cursor into `in`
  std::string out;
  size_t out_off = 0;
  // connection-abuse hardening (round 13): last byte activity and the
  // start of the oldest incomplete request (0 = none pending). The
  // idle timeout reaps silent keep-alive connections; the read timeout
  // bounds how long ONE request may take to arrive in full, which is
  // what defeats slowloris drips (each drip refreshes last_activity
  // but never completes the request).
  int64_t last_activity_ns = 0;
  int64_t request_start_ns = 0;
  // TLS termination (round 20): non-null ssl marks a TLS connection.
  // The handshake deadline anchors at accept_ns and is NEVER refreshed
  // by arriving bytes — a ClientHello dripped one byte at a time is the
  // slowloris shape moved down one layer, and it must die on the same
  // absolute clock no matter how diligently it drips.
  void* ssl = nullptr;   // SSL* (owns both BIOs once set_bio'd)
  void* rbio = nullptr;  // socket→SSL ciphertext
  void* wbio = nullptr;  // SSL→socket ciphertext
  TlsCtx* tls = nullptr;           // generation pinned at accept
  std::string enc_out;             // encrypted bytes awaiting send()
  size_t enc_off = 0;
  int64_t accept_ns = 0;           // handshake-arrival deadline anchor
  bool tls_established = false;    // SSL_do_handshake returned 1
  bool tls_shutdown_sent = false;  // close_notify already queued
  bool tls_fail_injected = false;  // `tls.handshake` failpoint armed
  bool reject_after_handshake = false;  // over-cap: 503 once readable
  bool want_write = false;
  bool closing = false;       // stop parsing further requests
  bool flush_queued = false;  // dedup marker within one process_comps pass
  std::deque<std::unique_ptr<PendingResp>> pipeline;

  // per-request parse state
  int state = 0;  // 0=head 1=body-cl 2=body-chunked
  bool http10 = false, req_close = false, chunked = false;
  int64_t content_length = -1;
  size_t body_start = 0;
  std::string chunk_body;
  int ch_state = 0;  // 0=size-line 1=data 2=data-crlf 3=trailer
  size_t ch_remaining = 0;
  int64_t total_body = 0;
  int route = -1;  // 0 validate 1 raw 2 audit; -1 miss; -2 method miss
  std::string policy_id;
  // incoming W3C traceparent header, carried verbatim across the ring
  // so Python parents the request's spans to the webhook caller's trace
  std::string traceparent;
  bool expect_continue = false;
};

// ------------------------------------------------------------------ loops --

struct Front;

struct Loop {
  Front* front;
  int idx;
  int ep = -1;
  int comp_efd = -1;
  std::thread thr;
  SubRing ring;
  CompStack comps;
  std::unordered_map<int, Conn*> conns;
  std::unordered_map<uint64_t, std::pair<Conn*, PendingResp*>> pending;
  uint64_t next_seq = 1;
  int64_t last_sweep_ns = 0;  // timeout sweep cadence (~1 s)
  bool listen_registered = false;
  // cached Date header value, rebuilt once per second
  time_t date_sec = 0;
  char date_buf[64] = {0};

  explicit Loop(size_t ring_bits) : ring(ring_bits) {}
};

constexpr int STAT_N = 24;

struct Front {
  int listen_fd;
  int n_loops;
  int64_t max_body;
  std::string server_hdr;
  std::vector<std::unique_ptr<Loop>> loops;
  StaticResp statics[ST_MAX];
  int sub_efd = -1;  // wakes the Python drainer
  std::atomic<bool> stop{false};
  std::atomic<bool> stop_accepting{false};
  // connection-abuse hardening knobs (httpfront_configure; 0 = off)
  std::atomic<int64_t> idle_timeout_ns{0};
  std::atomic<int64_t> read_timeout_ns{0};
  std::atomic<int64_t> max_conns{0};
  std::atomic<int64_t> live_conns{0};
  // TLS (round 20): the current SSL_CTX generation for NEW accepts.
  // The mutex is taken at accept and swap only — accept-rate, not
  // per-byte — so rotation never contends with the serving byte path.
  std::mutex tls_mu;
  TlsCtx* tls_current = nullptr;  // guarded by tls_mu
  std::atomic<int64_t> tls_handshake_timeout_ns{0};
  // `tls.handshake` failpoint: -1 = fail every handshake, n>0 = fail
  // the next n, 0 = disarmed
  std::atomic<long> tls_fail_next{0};
  std::atomic<int64_t> stats[STAT_N] = {};
};

enum {
  S_CONNS = 0, S_REQUESTS, S_PARSED, S_FALLBACKS, S_NATIVE_SER, S_PY_SER,
  S_RING_FULL, S_BAD_REQ, S_ROUTE_MISS, S_OVERSIZE, S_BYTES_IN, S_BYTES_OUT,
  S_FRAMING_NS, S_OUTSTANDING, S_DISCONNECTS, S_IDLE_CLOSES, S_CONN_CAP,
  // TLS termination (round 20) — fills the STAT_N=24 budget exactly
  S_TLS_CONNS, S_TLS_HS_OK, S_TLS_HS_FAIL, S_TLS_HS_TIMEOUT,
  S_TLS_HS_DISCONNECT, S_TLS_FAIL_INJECTED, S_TLS_CLEAN_CLOSES,
};
static_assert(S_TLS_CLEAN_CLOSES == STAT_N - 1,
              "stats enum must fit the ABI's fixed STAT_N slots");

void wake_fd(int fd) {
  uint64_t one = 1;
  ssize_t r = write(fd, &one, sizeof(one));
  (void)r;
}

const char* date_header(Loop* lp) {
  time_t now = time(nullptr);
  if (now != lp->date_sec) {
    lp->date_sec = now;
    tm g;
    gmtime_r(&now, &g);
    strftime(lp->date_buf, sizeof(lp->date_buf),
             "%a, %d %b %Y %H:%M:%S GMT", &g);
  }
  return lp->date_buf;
}

void build_head(Loop* lp, std::string& w, int status,
                const std::string& content_type, size_t body_len,
                int retry_after, const std::string& extra, bool http10,
                bool close_conn) {
  char line[160];
  int n = snprintf(line, sizeof(line), "HTTP/1.%c %d %s\r\n",
                   http10 ? '0' : '1', status, reason_of(status));
  w.append(line, (size_t)n);
  w += "Content-Type: ";
  w += content_type;
  w += "\r\n";
  w += extra;
  if (retry_after > 0) {
    n = snprintf(line, sizeof(line), "Retry-After: %d\r\n", retry_after);
    w.append(line, (size_t)n);
  }
  n = snprintf(line, sizeof(line), "Content-Length: %zu\r\n", body_len);
  w.append(line, (size_t)n);
  w += "Date: ";
  w += date_header(lp);
  w += "\r\nServer: ";
  w += lp->front->server_hdr;
  w += "\r\n";
  if (close_conn && !http10) w += "Connection: close\r\n";
  w += "\r\n";
}

void fill_response(Loop* lp, PendingResp* pr, int status,
                   const std::string& content_type, const std::string& body,
                   int retry_after, const std::string& extra) {
  pr->wire.clear();
  build_head(lp, pr->wire, status, content_type, body.size(), retry_after,
             extra, pr->http10, pr->close_after);
  pr->wire += body;
  pr->done = true;
}

void conn_destroy(Loop* lp, Conn* c, bool midbody) {
  for (auto& pr : c->pipeline) lp->pending.erase(pr->id);
  lp->conns.erase(c->fd);
  epoll_ctl(lp->ep, EPOLL_CTL_DEL, c->fd, nullptr);
  close(c->fd);
  lp->front->live_conns.fetch_add(-1, std::memory_order_relaxed);
  if (midbody)
    lp->front->stats[S_DISCONNECTS].fetch_add(1, std::memory_order_relaxed);
  if (c->ssl != nullptr) tls_api()->SSL_free(c->ssl);  // frees both BIOs
  if (c->tls != nullptr) tls_ctx_unref(c->tls);
  delete c;
}

void tls_flush(Loop* lp, Conn* c);

// Server-initiated clean close of a TLS connection: queue close_notify,
// best-effort flush it (one non-blocking send — the alert is ~2 dozen
// bytes), then tear down. Used by every path that CHOOSES to close
// (closing-complete, idle reap, conn-cap 503) so well-behaved clients
// see an orderly TLS EOF instead of a truncation-looking RST.
void tls_graceful_destroy(Loop* lp, Conn* c) {
  TlsApi* a = tls_api();
  if (!c->tls_shutdown_sent) {
    c->tls_shutdown_sent = true;
    // count at decision time, before the alert hits the wire: the
    // peer's clean-EOF observation must never precede the counter
    lp->front->stats[S_TLS_CLEAN_CLOSES].fetch_add(
        1, std::memory_order_relaxed);
    a->SSL_shutdown(c->ssl);
    while (a->BIO_ctrl_pending(c->wbio) > 0) {
      char buf[4096];
      int n = a->BIO_read(c->wbio, buf, sizeof(buf));
      if (n <= 0) break;
      c->enc_out.append(buf, (size_t)n);
    }
    if (c->enc_off < c->enc_out.size()) {
      ssize_t r = send(c->fd, c->enc_out.data() + c->enc_off,
                       c->enc_out.size() - c->enc_off, MSG_NOSIGNAL);
      if (r > 0)
        lp->front->stats[S_BYTES_OUT].fetch_add(r, std::memory_order_relaxed);
    }
  }
  conn_destroy(lp, c, false);
}

// flush completed head-of-line responses into the socket
void conn_flush(Loop* lp, Conn* c) {
  while (!c->pipeline.empty() && c->pipeline.front()->done) {
    c->out += c->pipeline.front()->wire;
    if (c->pipeline.front()->close_after) c->closing = true;
    c->pipeline.pop_front();
  }
  if (c->ssl != nullptr) {
    tls_flush(lp, c);
    return;
  }
  while (c->out_off < c->out.size()) {
    ssize_t n = send(c->fd, c->out.data() + c->out_off,
                     c->out.size() - c->out_off, MSG_NOSIGNAL);
    if (n > 0) {
      c->out_off += (size_t)n;
      lp->front->stats[S_BYTES_OUT].fetch_add(n, std::memory_order_relaxed);
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      if (!c->want_write) {
        epoll_event ev{};
        ev.events = EPOLLIN | EPOLLOUT;
        ev.data.fd = c->fd;
        epoll_ctl(lp->ep, EPOLL_CTL_MOD, c->fd, &ev);
        c->want_write = true;
      }
      return;
    }
    conn_destroy(lp, c, false);
    return;
  }
  c->out.clear();
  c->out_off = 0;
  if (c->want_write) {
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = c->fd;
    epoll_ctl(lp->ep, EPOLL_CTL_MOD, c->fd, &ev);
    c->want_write = false;
  }
  if (c->closing && c->pipeline.empty()) conn_destroy(lp, c, false);
}

// TLS half of conn_flush: encrypt pending plaintext through SSL_write,
// drain the write BIO, and push ciphertext to the socket with the same
// EAGAIN→EPOLLOUT discipline as the plaintext path. c->out/c->out_off
// hold PLAINTEXT not yet consumed by SSL_write; enc_out/enc_off hold
// ciphertext not yet accepted by the kernel.
void tls_flush(Loop* lp, Conn* c) {
  TlsApi* a = tls_api();
  Front* f = lp->front;
  if (c->tls_established && !c->tls_shutdown_sent) {
    while (c->out_off < c->out.size()) {
      size_t chunk = c->out.size() - c->out_off;
      if (chunk > (1u << 20)) chunk = 1u << 20;
      a->ERR_clear_error();
      int n = a->SSL_write(c->ssl, c->out.data() + c->out_off, (int)chunk);
      if (n <= 0) break;  // WANT_READ mid-rekey: retry after next read
      c->out_off += (size_t)n;
    }
    if (c->out_off >= c->out.size()) {
      c->out.clear();
      c->out_off = 0;
    }
  }
  while (a->BIO_ctrl_pending(c->wbio) > 0) {
    char buf[16384];
    int n = a->BIO_read(c->wbio, buf, sizeof(buf));
    if (n <= 0) break;
    c->enc_out.append(buf, (size_t)n);
  }
  while (c->enc_off < c->enc_out.size()) {
    ssize_t n = send(c->fd, c->enc_out.data() + c->enc_off,
                     c->enc_out.size() - c->enc_off, MSG_NOSIGNAL);
    if (n > 0) {
      c->enc_off += (size_t)n;
      f->stats[S_BYTES_OUT].fetch_add(n, std::memory_order_relaxed);
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      if (!c->want_write) {
        epoll_event ev{};
        ev.events = EPOLLIN | EPOLLOUT;
        ev.data.fd = c->fd;
        epoll_ctl(lp->ep, EPOLL_CTL_MOD, c->fd, &ev);
        c->want_write = true;
      }
      return;
    }
    conn_destroy(lp, c, false);
    return;
  }
  c->enc_out.clear();
  c->enc_off = 0;
  if (c->want_write) {
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = c->fd;
    epoll_ctl(lp->ep, EPOLL_CTL_MOD, c->fd, &ev);
    c->want_write = false;
  }
  if (c->closing && c->pipeline.empty() && c->out_off >= c->out.size())
    tls_graceful_destroy(lp, c);
}

// queue an immediate (statically known) response, preserving pipeline order
void respond_static_idx(Loop* lp, Conn* c, int slot, int64_t actual_body) {
  Front* f = lp->front;
  const StaticResp& st = f->statics[slot];
  auto pr = std::make_unique<PendingResp>();
  pr->id = 0;
  pr->close_after = c->req_close;
  pr->http10 = c->http10;
  std::string body = st.body;
  if (slot == ST_413 && body.find("%lld") != std::string::npos) {
    char tmp[256];
    snprintf(tmp, sizeof(tmp), body.c_str(), (long long)actual_body);
    body = tmp;
  }
  fill_response(lp, pr.get(), st.status, st.content_type, body, 0,
                st.extra);
  c->pipeline.push_back(std::move(pr));
}

// hand the parsed request to Python via the submission ring
void submit_request(Loop* lp, Conn* c, const std::string& body,
                    int64_t t_first) {
  Front* f = lp->front;
  int64_t t0 = now_ns();
  uint64_t id = ((uint64_t)(lp->idx & 0x7F) << 56) | lp->next_seq++;
  auto pr = std::make_unique<PendingResp>();
  pr->id = id;
  pr->close_after = c->req_close;
  pr->http10 = c->http10;
  uint8_t* rec = nullptr;
  if (c->route == 1) {  // validate_raw: Python parses the raw body
    rec = build_record(id, K_RAW, false, c->policy_id, "", "", "", "",
                       c->traceparent, body, t_first, t0, now_ns());
  } else {
    CanonResult cr;
    // ensure_ascii escaping can expand multibyte UTF-8 up to 3x: a
    // canonical payload larger than max_body (or any field beyond the
    // u16 wire-length fields) ships the RAW body instead — fallback
    // records are bounded by max_body, so they always fit the Python
    // drainer's poll buffer and the record header
    bool canon_ok = canon_admission_review(body.data(), body.size(), cr) &&
                    cr.payload.size() <= (size_t)f->max_body &&
                    cr.uid.size() <= 0xFFFF && cr.ns.size() <= 0xFFFF &&
                    cr.op.size() <= 0xFFFF && cr.gvk.size() <= 0xFFFF;
    if (canon_ok) {
      f->stats[S_PARSED].fetch_add(1, std::memory_order_relaxed);
      rec = build_record(id, c->route == 2 ? K_AUDIT : K_VALIDATE, cr.has_ns,
                         c->policy_id, cr.uid, cr.ns, cr.op, cr.gvk,
                         c->traceparent, cr.payload, t_first, t0, now_ns());
    } else {
      f->stats[S_FALLBACKS].fetch_add(1, std::memory_order_relaxed);
      rec = build_record(id, c->route == 2 ? K_AUDIT_FB : K_VALIDATE_FB,
                         false, c->policy_id, "", "", "", "",
                         c->traceparent, body, t_first, t0, now_ns());
    }
  }
  if (rec == nullptr) {
    // a field overflowed its wire slot (build_record refuses to
    // truncate): answer 400 in-band — the request is malformed, and a
    // silent drop would read as a network fault
    f->stats[S_BAD_REQ].fetch_add(1, std::memory_order_relaxed);
    PendingResp* raw_pr = pr.get();
    c->pipeline.push_back(std::move(pr));
    const StaticResp& st = f->statics[ST_400];
    fill_response(lp, raw_pr, st.status, st.content_type, st.body, 0,
                  st.extra);
    f->stats[S_FRAMING_NS].fetch_add(now_ns() - t0,
                                     std::memory_order_relaxed);
    return;
  }
  int pushed = lp->ring.push(rec);
  if (pushed < 0) {
    free(rec);
    f->stats[S_RING_FULL].fetch_add(1, std::memory_order_relaxed);
    PendingResp* raw_pr = pr.get();
    c->pipeline.push_back(std::move(pr));
    const StaticResp& st = f->statics[ST_503];
    fill_response(lp, raw_pr, st.status, st.content_type, st.body, 0,
                  st.extra);
    f->stats[S_FRAMING_NS].fetch_add(now_ns() - t0,
                                     std::memory_order_relaxed);
    return;
  }
  f->stats[S_OUTSTANDING].fetch_add(1, std::memory_order_relaxed);
  lp->pending.emplace(id, std::make_pair(c, pr.get()));
  c->pipeline.push_back(std::move(pr));
  f->stats[S_FRAMING_NS].fetch_add(now_ns() - t0, std::memory_order_relaxed);
  (void)pushed;  // the drainer polls the rings at 1 ms ticks — no wake
                 // syscall per request (see push_comp for the rationale)
}

// finish the current request: route it, reset per-request parse state
void finish_request(Loop* lp, Conn* c, const std::string& body) {
  Front* f = lp->front;
  f->stats[S_REQUESTS].fetch_add(1, std::memory_order_relaxed);
  // flight recorder: the read-timeout clock doubles as the request's
  // arrival stamp (first byte of an incomplete request) — capture it
  // before the reset below zeroes it
  int64_t t_first = c->request_start_ns;
  // a request ARRIVED in full: reset the read-timeout clock so a
  // healthy client pipelining back-to-back requests (whose buffer
  // never drains to a clean boundary) is not reaped mid-stream; the
  // post-parse bookkeeping re-arms it from NOW for any partial tail
  c->request_start_ns = 0;
  // route misses FIRST: aiohttp 404/405s without ever reading the body,
  // so an oversized body on an unknown route must still answer 404
  if (c->route == -1) {
    f->stats[S_ROUTE_MISS].fetch_add(1, std::memory_order_relaxed);
    respond_static_idx(lp, c, ST_404, 0);
  } else if (c->route == -2) {
    f->stats[S_ROUTE_MISS].fetch_add(1, std::memory_order_relaxed);
    respond_static_idx(lp, c, ST_405, 0);
  } else if ((int64_t)body.size() > f->max_body ||
             c->total_body > f->max_body) {
    f->stats[S_OVERSIZE].fetch_add(1, std::memory_order_relaxed);
    respond_static_idx(lp, c, ST_413,
                       std::max((int64_t)body.size(), c->total_body));
  } else {
    submit_request(lp, c, body, t_first);
  }
  if (c->req_close || c->http10) c->closing = true;  // parse no further
  c->state = 0;
  c->http10 = false;
  c->req_close = false;
  c->chunked = false;
  c->content_length = -1;
  c->chunk_body.clear();
  c->ch_state = 0;
  c->ch_remaining = 0;
  c->total_body = 0;
  c->route = -1;
  c->policy_id.clear();
  c->traceparent.clear();
  c->expect_continue = false;
}

void bad_request(Loop* lp, Conn* c) {
  Front* f = lp->front;
  f->stats[S_BAD_REQ].fetch_add(1, std::memory_order_relaxed);
  f->stats[S_REQUESTS].fetch_add(1, std::memory_order_relaxed);
  c->req_close = true;
  respond_static_idx(lp, c, ST_400, 0);
  c->closing = true;
}

// case-insensitive ASCII compare
bool ieq(const char* a, size_t alen, const char* b) {
  size_t blen = strlen(b);
  if (alen != blen) return false;
  for (size_t i = 0; i < alen; i++)
    if (tolower((unsigned char)a[i]) != tolower((unsigned char)b[i]))
      return false;
  return true;
}

// Parse as many complete requests as the input buffer holds. Returns false
// when the connection was destroyed.
// graftcheck: wire-input
bool conn_parse(Loop* lp, Conn* c) {
  Front* f = lp->front;
  constexpr size_t MAX_HEAD = 64 * 1024;
  for (;;) {
    if (c->closing) break;  // drop pipelined bytes after a close-marked
                            // request — but still flush responses below
    const char* base = c->in.data();
    size_t avail = c->in.size() - c->off;
    if (c->state == 0) {
      if (avail == 0) break;
      const char* head = base + c->off;
      const char* hdr_end =
          (const char*)memmem(head, avail, "\r\n\r\n", 4);
      if (hdr_end == nullptr) {
        if (avail > MAX_HEAD) { bad_request(lp, c); continue; }
        break;  // need more bytes
      }
      int64_t t0 = now_ns();
      size_t head_len = (size_t)(hdr_end - head) + 4;
      // request line
      const char* eol = (const char*)memchr(head, '\r', head_len);
      const char* sp1 = (const char*)memchr(head, ' ', (size_t)(eol - head));
      if (sp1 == nullptr) { bad_request(lp, c); continue; }
      const char* sp2 = (const char*)memchr(
          sp1 + 1, ' ', (size_t)(eol - sp1 - 1));
      if (sp2 == nullptr) { bad_request(lp, c); continue; }
      std::string method(head, (size_t)(sp1 - head));
      std::string path(sp1 + 1, (size_t)(sp2 - sp1 - 1));
      std::string version(sp2 + 1, (size_t)(eol - sp2 - 1));
      bool ok_method = true;
      for (char ch : method)
        if (!(ch >= 'A' && ch <= 'Z')) ok_method = false;
      if (method.empty() || !ok_method) { bad_request(lp, c); continue; }
      if (version == "HTTP/1.0") c->http10 = true;
      else if (version != "HTTP/1.1") { bad_request(lp, c); continue; }
      // headers
      const char* hp = eol + 2;
      bool have_te = false;
      bool keep_alive_hdr = false;
      while (hp < hdr_end + 2) {
        const char* he = (const char*)memchr(
            hp, '\r', (size_t)(hdr_end + 2 - hp));
        if (he == nullptr || he == hp) break;
        const char* colon = (const char*)memchr(hp, ':', (size_t)(he - hp));
        if (colon == nullptr) { hp = he + 2; continue; }
        const char* v = colon + 1;
        while (v < he && (*v == ' ' || *v == '\t')) v++;
        size_t nlen = (size_t)(colon - hp), vlen = (size_t)(he - v);
        if (ieq(hp, nlen, "content-length")) {
          // duplicate Content-Length is a request-smuggling vector and
          // llhttp (the Python frontend's parser) rejects it — parity
          // demands a 400, not last-wins
          if (c->content_length >= 0) { bad_request(lp, c); goto next_iter; }
          char tmp[24];
          if (vlen == 0 || vlen >= sizeof(tmp)) { bad_request(lp, c); goto next_iter; }
          memcpy(tmp, v, vlen);
          tmp[vlen] = 0;
          char* endp = nullptr;
          long long cl = strtoll(tmp, &endp, 10);
          if (*endp != 0 || cl < 0) { bad_request(lp, c); goto next_iter; }
          c->content_length = cl;
        } else if (ieq(hp, nlen, "transfer-encoding")) {
          have_te = true;
          if (ieq(v, vlen, "chunked")) c->chunked = true;
        } else if (ieq(hp, nlen, "connection")) {
          if (ieq(v, vlen, "close")) c->req_close = true;
          else if (ieq(v, vlen, "keep-alive")) keep_alive_hdr = true;
        } else if (ieq(hp, nlen, "expect")) {
          if (ieq(v, vlen, "100-continue")) c->expect_continue = true;
        } else if (ieq(hp, nlen, "traceparent")) {
          // carried verbatim but GATED to printable ASCII (bounded: the
          // W3C form is 55 chars; a malformed oversize value is
          // dropped, never truncated into something that parses).
          // HTTP/1.1 field values legally carry obs-text bytes
          // 0x80-0xFF — those must never cross the ring, or Python's
          // strict decode would kill the drainer on attacker input.
          bool clean = vlen <= 128;
          for (size_t ti = 0; clean && ti < vlen; ti++) {
            unsigned char ch = (unsigned char)v[ti];
            if (ch < 0x20 || ch > 0x7e) clean = false;
          }
          if (clean) c->traceparent.assign(v, vlen);
        }
        hp = he + 2;
      }
      if (have_te && !c->chunked) { bad_request(lp, c); continue; }
      if (c->chunked && c->content_length >= 0) {
        bad_request(lp, c);  // CL + chunked: the other smuggling vector
        continue;
      }
      (void)keep_alive_hdr;  // HTTP/1.0 closes either way (finish_request)
      // routing (query strings stripped): one segment is a policy id,
      // two non-empty segments are "tenant/policy" (round-16 tenant
      // routing — the Python sink resolves the tenant and answers the
      // same 404 body as the aiohttp router for unknown names)
      size_t q = path.find('?');
      if (q != std::string::npos) path.resize(q);
      c->route = -1;
      const struct { const char* prefix; int route; } routes[] = {
          {"/validate_raw/", 1}, {"/validate/", 0}, {"/audit/", 2}};
      for (const auto& r : routes) {
        size_t pl = strlen(r.prefix);
        if (path.compare(0, pl, r.prefix) == 0 && path.size() > pl) {
          size_t slash = path.find('/', pl);
          bool one_seg = slash == std::string::npos;
          bool two_seg = !one_seg && slash > pl && slash + 1 < path.size() &&
                         path.find('/', slash + 1) == std::string::npos;
          if (one_seg || two_seg) {
            c->route = r.route;
            c->policy_id = path.substr(pl);
            break;
          }
        }
      }
      if (c->route >= 0 && c->policy_id.size() > 4096) {
        // a policy id is a (tenant-qualified) resource name — K8s names
        // top out at 253 chars. A multi-KB segment is abuse, and the
        // record header's u16 length slot must never be asked to carry
        // anything near the 64 KiB header cap: unknown-name 404, same
        // as the aiohttp router.
        c->route = -1;
        c->policy_id.clear();
      }
      if (c->route >= 0 && method != "POST") c->route = -2;
      c->off += head_len;
      f->stats[S_FRAMING_NS].fetch_add(now_ns() - t0,
                                       std::memory_order_relaxed);
      if (c->expect_continue && c->pipeline.empty() &&
          c->out.size() == c->out_off) {
        // interim response ONLY when nothing earlier is pending on this
        // connection: appending it with responses outstanding would
        // jump the pipeline's ordered slots. A pipelining client that
        // sent Expect alongside later requests already pushed its body;
        // RFC 7231 §5.1.1 forbids it waiting indefinitely for the 100.
        c->out += c->http10 ? "HTTP/1.0 100 Continue\r\n\r\n"
                            : "HTTP/1.1 100 Continue\r\n\r\n";
      }
      if (c->chunked) {
        c->state = 2;
      } else if (c->content_length > 0) {
        c->state = 1;
        c->total_body = c->content_length;
      } else {
        std::string empty;
        finish_request(lp, c, empty);
      }
      continue;
    }
    if (c->state == 1) {  // content-length body
      size_t need = (size_t)c->content_length;
      if (c->content_length > f->max_body) {
        // oversized declared body: drain from the wire WITHOUT buffering
        // (aiohttp keeps the connection usable after its 413), but bound
        // the drain — a multi-GB declaration answers 413 and closes
        if (c->content_length > f->max_body * 8 ||
            c->content_length > (int64_t)(64u << 20)) {
          c->req_close = true;
          std::string empty;
          finish_request(lp, c, empty);  // total_body carries the size
          continue;
        }
        if (c->ch_remaining == 0) c->ch_remaining = need;
        size_t take = avail < c->ch_remaining ? avail : c->ch_remaining;
        c->off += take;
        c->ch_remaining -= take;
        if (c->ch_remaining > 0) break;  // keep draining
        std::string empty;
        finish_request(lp, c, empty);
        continue;
      }
      if (avail < need) break;
      std::string body(base + c->off, need);
      c->off += need;
      finish_request(lp, c, body);
      continue;
    }
    // chunked body: size line -> data -> CRLF, 0-chunk then trailer
    // lines until an empty one. Decoded bytes accumulate in chunk_body
    // (capped just past max_body; the 413 text still needs the TOTAL).
    {
      bool fatal = false;
      for (;;) {
        const char* p = c->in.data() + c->off;
        const char* end = c->in.data() + c->in.size();
        if (c->ch_state == 0) {  // chunk-size line
          const char* nl =
              (const char*)memmem(p, (size_t)(end - p), "\r\n", 2);
          if (nl == nullptr) break;
          std::string sz(p, (size_t)(nl - p));
          size_t semi = sz.find(';');
          if (semi != std::string::npos) sz.resize(semi);
          char* endp = nullptr;
          unsigned long long v = strtoull(sz.c_str(), &endp, 16);
          if (endp == sz.c_str() || *endp != 0) { fatal = true; break; }
          c->off = (size_t)(nl + 2 - c->in.data());
          if (v == 0) { c->ch_state = 3; continue; }
          c->ch_remaining = (size_t)v;
          c->ch_state = 1;
          continue;
        }
        if (c->ch_state == 1) {  // chunk data
          size_t have = (size_t)(end - p);
          if (have == 0) break;
          size_t take = have < c->ch_remaining ? have : c->ch_remaining;
          if ((int64_t)(c->chunk_body.size() + take) <=
              lp->front->max_body + 4096)
            c->chunk_body.append(p, take);
          c->total_body += (int64_t)take;
          if (c->total_body > lp->front->max_body * 8 &&
              c->total_body > (int64_t)(64u << 20)) {
            fatal = true;  // unbounded chunk stream: stop counting, close
            break;
          }
          c->ch_remaining -= take;
          c->off += take;
          if (c->ch_remaining > 0) break;  // need more data
          c->ch_state = 2;
          continue;
        }
        if (c->ch_state == 2) {  // CRLF terminating the chunk data
          if (end - p < 2) break;
          if (p[0] != '\r' || p[1] != '\n') { fatal = true; break; }
          c->off += 2;
          c->ch_state = 0;
          continue;
        }
        // ch_state == 3: trailer lines until an empty one
        const char* nl =
            (const char*)memmem(p, (size_t)(end - p), "\r\n", 2);
        if (nl == nullptr) break;
        bool empty = (nl == p);
        c->off = (size_t)(nl + 2 - c->in.data());
        if (empty) {
          std::string body;
          body.swap(c->chunk_body);
          finish_request(lp, c, body);
          c->ch_state = 0;
          break;
        }
        continue;
      }
      if (fatal) { bad_request(lp, c); continue; }
      if (c->state == 2) break;  // body still incomplete: need more bytes
      continue;  // request finished: parse the next pipelined one
    }
  next_iter:
    continue;
  }
  // read-timeout bookkeeping: a request is "pending" while a body is
  // incomplete (state != 0) or a partial head sits unconsumed — the
  // clock starts at the first such observation, each completed request
  // zeroes it (finish_request), and it clears when the buffer drains to
  // a clean boundary; slowloris drips keep ONE request incomplete, so
  // their clock is never reset
  bool pending_req =
      !c->closing && (c->state != 0 || c->off < c->in.size());
  if (pending_req) {
    if (c->request_start_ns == 0) c->request_start_ns = now_ns();
  } else {
    c->request_start_ns = 0;
  }
  // compact the input buffer
  if (c->off == c->in.size()) {
    c->in.clear();
    c->off = 0;
  } else if (c->off > 1 << 16) {
    c->in.erase(0, c->off);
    c->off = 0;
  }
  conn_flush(lp, c);
  return true;
}

// --------------------------------------------------------- loop machinery --

// best-effort in-band reject for connections over the cap: one
// non-blocking send of a canned 503, then close — a silent close would
// read as a network fault, not an explicit server decision
void reject_over_cap(Front* f, int fd) {
  static const char kBody[] =
      "{\"message\": \"connection limit reached; retry later\", "
      "\"status\": 503}";
  char wire[256];
  int n = snprintf(wire, sizeof(wire),
                   "HTTP/1.1 503 Service Unavailable\r\n"
                   "Content-Type: application/json; charset=utf-8\r\n"
                   "Content-Length: %zu\r\nRetry-After: 1\r\n"
                   "Connection: close\r\n\r\n%s",
                   sizeof(kBody) - 1, kBody);
  // count BEFORE the send: the client's read of this 503 (or the EOF
  // from close) must never win a race against the counter — tests and
  // operators read stats the instant the rejection is observable
  f->stats[S_CONN_CAP].fetch_add(1, std::memory_order_relaxed);
  ssize_t r = send(fd, wire, (size_t)n, MSG_NOSIGNAL);
  (void)r;
  close(fd);
}

void do_accept(Loop* lp) {
  Front* f = lp->front;
  for (;;) {
    int fd = accept4(f->listen_fd, nullptr, nullptr,
                     SOCK_NONBLOCK | SOCK_CLOEXEC);
    if (fd < 0) break;  // EAGAIN / another loop won the race
    // pin the CURRENT TLS generation before the cap decision: an
    // over-cap TLS accept must still handshake, because its in-band 503
    // is unreadable until the session keys exist
    TlsCtx* tctx = nullptr;
    {
      std::lock_guard<std::mutex> g(f->tls_mu);
      if (f->tls_current != nullptr) {
        f->tls_current->refs.fetch_add(1, std::memory_order_relaxed);
        tctx = f->tls_current;
      }
    }
    int64_t cap = f->max_conns.load(std::memory_order_relaxed);
    int64_t live = f->live_conns.load(std::memory_order_relaxed);
    bool over_cap = cap > 0 && live >= cap;
    if (over_cap && tctx == nullptr) {
      reject_over_cap(f, fd);
      continue;
    }
    if (over_cap && live >= cap + 64) {
      // the close_notify-clean 503 needs a live handshake, which costs
      // state — past a bounded grace pool the only safe answer to a
      // connection flood is the plain close the cap exists to deliver
      close(fd);
      tls_ctx_unref(tctx);
      f->stats[S_CONN_CAP].fetch_add(1, std::memory_order_relaxed);
      continue;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    Conn* c = new Conn();
    c->fd = fd;
    c->last_activity_ns = now_ns();
    if (tctx != nullptr) {
      TlsApi* a = tls_api();
      void* ssl = a->SSL_new(tctx->ctx);
      void* rb = ssl != nullptr ? a->BIO_new(a->BIO_s_mem()) : nullptr;
      void* wb = rb != nullptr ? a->BIO_new(a->BIO_s_mem()) : nullptr;
      if (wb == nullptr) {
        if (rb != nullptr) a->BIO_free(rb);
        if (ssl != nullptr) a->SSL_free(ssl);
        tls_ctx_unref(tctx);
        close(fd);
        delete c;
        continue;
      }
      a->SSL_set_bio(ssl, rb, wb);  // ssl owns both BIOs from here
      a->SSL_set_accept_state(ssl);
      c->ssl = ssl;
      c->rbio = rb;
      c->wbio = wb;
      c->tls = tctx;
      c->accept_ns = now_ns();
      c->reject_after_handshake = over_cap;
      f->stats[S_TLS_CONNS].fetch_add(1, std::memory_order_relaxed);
      // `tls.handshake` failpoint: claim one injected failure slot
      long fn = f->tls_fail_next.load(std::memory_order_relaxed);
      while (fn != 0) {
        if (fn < 0) {
          c->tls_fail_injected = true;
          break;
        }
        if (f->tls_fail_next.compare_exchange_weak(
                fn, fn - 1, std::memory_order_relaxed)) {
          c->tls_fail_injected = true;
          break;
        }
      }
    }
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = fd;
    if (epoll_ctl(lp->ep, EPOLL_CTL_ADD, fd, &ev) != 0) {
      close(fd);
      if (c->ssl != nullptr) tls_api()->SSL_free(c->ssl);
      if (c->tls != nullptr) tls_ctx_unref(c->tls);
      delete c;
      continue;
    }
    lp->conns[fd] = c;
    f->live_conns.fetch_add(1, std::memory_order_relaxed);
    f->stats[S_CONNS].fetch_add(1, std::memory_order_relaxed);
  }
}

// TLS read path: pump ciphertext into the read BIO, run the handshake
// state machine until established, then SSL_read plaintext into the
// SAME c->in the plaintext parser consumes — everything downstream of
// the record layer is shared with the plaintext frontend byte for byte.
// graftcheck: wire-input
void tls_conn_read(Loop* lp, Conn* c) {
  Front* f = lp->front;
  TlsApi* a = tls_api();
  char buf[65536];
  c->last_activity_ns = now_ns();
  bool peer_closed = false;
  for (;;) {
    ssize_t n = recv(c->fd, buf, sizeof(buf), 0);
    if (n > 0) {
      f->stats[S_BYTES_IN].fetch_add(n, std::memory_order_relaxed);
      a->BIO_write(c->rbio, buf, (int)n);
      if (n < (ssize_t)sizeof(buf)) break;
      continue;
    }
    if (n == 0 || !(errno == EAGAIN || errno == EWOULDBLOCK)) {
      peer_closed = true;  // EOF and hard errors both end the conn below
      break;
    }
    break;
  }
  if (c->tls_fail_injected) {
    // `tls.handshake` failpoint: refuse the handshake outright — the
    // client observes a connection torn down mid-handshake, the server
    // accounts it as an injected failure, never a mystery
    f->stats[S_TLS_FAIL_INJECTED].fetch_add(1, std::memory_order_relaxed);
    f->stats[S_TLS_HS_FAIL].fetch_add(1, std::memory_order_relaxed);
    conn_destroy(lp, c, false);
    return;
  }
  if (!c->tls_established) {
    a->ERR_clear_error();
    int r = a->SSL_do_handshake(c->ssl);
    if (r == 1) {
      c->tls_established = true;
      f->stats[S_TLS_HS_OK].fetch_add(1, std::memory_order_relaxed);
      if (c->reject_after_handshake) {
        // over-cap accept: the 503 is finally READABLE — the same
        // message + Retry-After the plaintext cap sends, answered
        // in-band and closed with close_notify
        f->stats[S_CONN_CAP].fetch_add(1, std::memory_order_relaxed);
        c->req_close = true;
        auto pr = std::make_unique<PendingResp>();
        pr->id = 0;
        pr->close_after = true;
        fill_response(lp, pr.get(), 503,
                      "application/json; charset=utf-8",
                      "{\"message\": \"connection limit reached; retry "
                      "later\", \"status\": 503}",
                      1, "");
        c->pipeline.push_back(std::move(pr));
        c->closing = true;
        conn_flush(lp, c);  // flushes Finished + 503, then clean-closes
        return;
      }
    } else {
      int err = a->SSL_get_error(c->ssl, r);
      if (err != kSSL_ERROR_WANT_READ && err != kSSL_ERROR_WANT_WRITE) {
        // hard handshake failure (garbage record, protocol floor,
        // wrong-CA client cert): flush the pending alert so the peer
        // sees a TLS alert rather than a bare RST, count, drop
        f->stats[S_TLS_HS_FAIL].fetch_add(1, std::memory_order_relaxed);
        while (a->BIO_ctrl_pending(c->wbio) > 0) {
          int n = a->BIO_read(c->wbio, buf, sizeof(buf));
          if (n <= 0) break;
          c->enc_out.append(buf, (size_t)n);
        }
        if (c->enc_off < c->enc_out.size()) {
          ssize_t sr = send(c->fd, c->enc_out.data() + c->enc_off,
                            c->enc_out.size() - c->enc_off, MSG_NOSIGNAL);
          if (sr > 0)
            f->stats[S_BYTES_OUT].fetch_add(sr, std::memory_order_relaxed);
        }
        conn_destroy(lp, c, false);
        return;
      }
      if (peer_closed) {
        f->stats[S_TLS_HS_DISCONNECT].fetch_add(1,
                                                std::memory_order_relaxed);
        conn_destroy(lp, c, false);
        return;
      }
      conn_flush(lp, c);  // push ServerHello…Finished; wait for more
      return;
    }
  }
  // established: drain every full record into the plaintext buffer
  bool tls_eof = false;
  for (;;) {
    int n = a->SSL_read(c->ssl, buf, sizeof(buf));
    if (n > 0) {
      c->in.append(buf, (size_t)n);
      continue;
    }
    int err = a->SSL_get_error(c->ssl, n);
    if (err == kSSL_ERROR_WANT_READ || err == kSSL_ERROR_WANT_WRITE) break;
    tls_eof = true;  // close_notify (ZERO_RETURN) or corrupt record
    break;
  }
  if (peer_closed || tls_eof) {
    bool midbody = c->state != 0;
    conn_destroy(lp, c, midbody);
    return;
  }
  conn_parse(lp, c);  // flushes via conn_flush→tls_flush; may destroy
}

// graftcheck: wire-input
void conn_read(Loop* lp, Conn* c) {
  if (c->ssl != nullptr) {
    tls_conn_read(lp, c);
    return;
  }
  char buf[65536];
  c->last_activity_ns = now_ns();
  for (;;) {
    ssize_t n = recv(c->fd, buf, sizeof(buf), 0);
    if (n > 0) {
      lp->front->stats[S_BYTES_IN].fetch_add(n, std::memory_order_relaxed);
      c->in.append(buf, (size_t)n);
      if (n < (ssize_t)sizeof(buf)) break;
      continue;
    }
    if (n == 0) {
      // peer closed; a request cut off mid-body simply dies (the Python
      // frontend behaves the same way — no response to compare)
      bool midbody = c->state != 0;
      conn_destroy(lp, c, midbody);
      return;
    }
    if (errno == EAGAIN || errno == EWOULDBLOCK) break;
    conn_destroy(lp, c, false);
    return;
  }
  conn_parse(lp, c);  // may destroy the conn via conn_flush
}

// reap abusive/idle connections (round 13): idle keep-alive conns past
// the idle timeout, and conns whose CURRENT request has been arriving
// for longer than the read timeout (slowloris drips). Runs ~1/s per
// loop — O(conns) at sweep cadence, not per tick.
void sweep_timeouts(Loop* lp, int64_t now) {
  Front* f = lp->front;
  int64_t idle = f->idle_timeout_ns.load(std::memory_order_relaxed);
  int64_t readt = f->read_timeout_ns.load(std::memory_order_relaxed);
  int64_t hst = f->tls_handshake_timeout_ns.load(std::memory_order_relaxed);
  if (idle <= 0 && readt <= 0 && hst <= 0) return;
  std::vector<Conn*> victims;
  std::vector<Conn*> hs_victims;
  for (auto& kv : lp->conns) {
    Conn* c = kv.second;
    // TLS handshake-arrival deadline: anchored at ACCEPT, never
    // refreshed — a dripped ClientHello is slowloris one layer down
    // and dies on the same absolute clock as a silent socket
    if (c->ssl != nullptr && !c->tls_established && hst > 0 &&
        now - c->accept_ns > hst) {
      hs_victims.push_back(c);
      continue;
    }
    if (readt > 0 && c->request_start_ns != 0 &&
        now - c->request_start_ns > readt) {
      victims.push_back(c);
      continue;
    }
    // idle applies only BETWEEN requests: nothing half-read and no
    // response outstanding (a conn waiting on a slow verdict is the
    // batcher deadline machinery's problem, not an idle abuser)
    if (idle > 0 && c->request_start_ns == 0 && c->pipeline.empty() &&
        now - c->last_activity_ns > idle) {
      victims.push_back(c);
    }
  }
  for (Conn* c : hs_victims) {
    f->stats[S_TLS_HS_TIMEOUT].fetch_add(1, std::memory_order_relaxed);
    conn_destroy(lp, c, false);
  }
  for (Conn* c : victims) {
    f->stats[S_IDLE_CLOSES].fetch_add(1, std::memory_order_relaxed);
    // a server-chosen close of an established TLS conn says so with
    // close_notify — reaped abusers still deserve a decodable EOF
    if (c->ssl != nullptr && c->tls_established)
      tls_graceful_destroy(lp, c);
    else
      conn_destroy(lp, c, false);
  }
}

void process_comps(Loop* lp) {
  Front* f = lp->front;
  Comp* c = lp->comps.take_all_reversed();
  if (c == nullptr) return;
  // two phases: fill every response, then flush each touched connection
  // ONCE — under pipelining a conn collects many completions per burst,
  // and send() is expensive on syscall-intercepting kernels
  std::vector<Conn*> touched;
  int64_t t0 = now_ns();
  while (c != nullptr) {
    Comp* nx = c->next;
    f->stats[S_OUTSTANDING].fetch_add(-1, std::memory_order_relaxed);
    auto it = lp->pending.find(c->req_id);
    if (it != lp->pending.end()) {
      Conn* conn = it->second.first;
      PendingResp* pr = it->second.second;
      lp->pending.erase(it);
      fill_response(lp, pr, c->status,
                    "application/json; charset=utf-8", c->body,
                    c->retry_after, "");
      if (!conn->flush_queued) {
        conn->flush_queued = true;
        touched.push_back(conn);
      }
    }
    delete c;
    c = nx;
  }
  f->stats[S_FRAMING_NS].fetch_add(now_ns() - t0, std::memory_order_relaxed);
  for (Conn* conn : touched) {
    conn->flush_queued = false;
    conn_flush(lp, conn);  // may destroy conn (it is not revisited)
  }
}

void loop_main(Loop* lp) {
  Front* f = lp->front;
  epoll_event evs[128];
  while (!f->stop.load(std::memory_order_acquire)) {
    if (f->stop_accepting.load(std::memory_order_relaxed) &&
        lp->listen_registered) {
      epoll_ctl(lp->ep, EPOLL_CTL_DEL, f->listen_fd, nullptr);
      lp->listen_registered = false;
    }
    // 1 ms tick: completions (and stop flags) are picked up by POLLING —
    // producers never pay a wake syscall (see push_comp)
    int n = epoll_wait(lp->ep, evs, 128, 1);
    process_comps(lp);
    {
      int64_t now = now_ns();
      if (now - lp->last_sweep_ns >= 1000000000ll) {
        lp->last_sweep_ns = now;
        sweep_timeouts(lp, now);
      }
    }
    for (int i = 0; i < n; i++) {
      int fd = evs[i].data.fd;
      if (fd == f->listen_fd) {
        if (!f->stop_accepting.load(std::memory_order_relaxed))
          do_accept(lp);
        continue;
      }
      if (fd == lp->comp_efd) {
        uint64_t v;
        ssize_t r = read(lp->comp_efd, &v, sizeof(v));
        (void)r;  // completions already drained above
        continue;
      }
      auto it = lp->conns.find(fd);
      if (it == lp->conns.end()) continue;
      Conn* c = it->second;
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
        // let recv() observe the condition (may still carry final bytes)
        conn_read(lp, c);
        continue;
      }
      if (evs[i].events & EPOLLOUT) {
        conn_flush(lp, c);
        if (lp->conns.find(fd) == lp->conns.end()) continue;  // destroyed
      }
      if (evs[i].events & EPOLLIN) conn_read(lp, c);
    }
  }
  // teardown: drop every connection (their futures were resolved — or
  // rejected — by the batcher shutdown before the loops are stopped)
  std::vector<Conn*> cs;
  cs.reserve(lp->conns.size());
  for (auto& kv : lp->conns) cs.push_back(kv.second);
  for (Conn* c : cs) conn_destroy(lp, c, false);
}

// Multi-shard completion contract (round 22, runtime/shards.py): with
// --serving-shards M > 1 completions for one frontend arrive from M
// independent dispatch/delivery threads plus the router's heartbeat
// thread (fence-time 503s). That is already safe here — the CompStack
// is a lock-free multi-producer stack and req_id routing is loop-local
// — but it relies on the Python side's exactly-once guarantee: a row's
// owner token (_Pending.owner) ensures at most one shard (or the
// router) ever calls complete()/fill for a given req_id, so this layer
// never needs dedup. Retry-After is written for any status when
// retry_after > 0 (429 shed and 503 fence share the path).
void push_comp(Front* f, uint64_t req_id, int status, int retry_after,
               std::string&& body) {
  int idx = (int)((req_id >> 56) & 0x7F);
  if (idx >= (int)f->loops.size()) return;
  Comp* c = new Comp{nullptr, req_id, status, retry_after, std::move(body)};
  // NO eventfd wake per completion: on syscall-intercepting kernels
  // (gVisor-class, ~10-25us/syscall) the wake dominated the whole
  // serving profile. The event loop polls the stack every iteration at
  // a 1 ms epoll timeout instead — bounded added latency, zero producer
  // syscalls. stop() still wakes the efd to exit promptly.
  f->loops[(size_t)idx]->comps.push(c);
}

}  // namespace

// ------------------------------------------------------------------ C ABI --

// Batch-granular native response assembly (round 19): parse ONE v2
// verdict record at buf+off (bounds-checked against len), advance off,
// and build the byte-exact json.dumps(AdmissionReviewResponse(...)
// .to_dict()) body — default separators, key order pinned to the Python
// to_dict() (graftcheck RS02 checks the literal key sequence below
// against models/admission.py). Record layout
// (runtime/native_frontend.py _BULK_REC — the one packing path):
//   u64 req_id | u8 allowed | u8 raw_shape | u8 flags | u8 n_warnings
//   | i32 code | i32 uid_len | i32 msg_len | i32 patch_len
//   | i32 reason_len | i32 n_causes
//   | uid | msg | patch | reason
//   | n_warnings x (u32 len | bytes)
//   | n_causes  x (i32 field_len | i32 msg_len | field | msg)
// -1 lengths mean the field is absent; flags bit0 = status object
// present (possibly empty), bit1 = warnings list present (possibly
// empty); a present patch always renders patchType "JSONPatch" (the
// Python packer refuses anything else). auditAnnotations never travels
// natively — the Python responder stays the oracle for it.
// graftcheck: abi(policy_server_tpu/runtime/native_frontend.py:_BULK_REC)
// graftcheck: wire-input
static bool parse_verdict_record(const uint8_t* buf, int64_t len,
                                 int64_t& off, uint64_t& req_id,
                                 std::string& body) {
  if (off + 36 > len) return false;
  memcpy(&req_id, buf + off, 8);
  uint8_t allowed = buf[off + 8];
  uint8_t raw_shape = buf[off + 9];
  uint8_t flags = buf[off + 10];
  uint8_t n_warn = buf[off + 11];
  int32_t code, uid_len, msg_len, patch_len, reason_len, n_causes;
  memcpy(&code, buf + off + 12, 4);
  memcpy(&uid_len, buf + off + 16, 4);
  memcpy(&msg_len, buf + off + 20, 4);
  memcpy(&patch_len, buf + off + 24, 4);
  memcpy(&reason_len, buf + off + 28, 4);
  memcpy(&n_causes, buf + off + 32, 4);
  off += 36;
  if (uid_len < 0) return false;
  auto take = [&](int32_t n, const uint8_t*& p) -> bool {
    if (n < 0) {
      p = nullptr;
      return true;
    }
    if (off + n > len) return false;
    p = buf + off;
    off += n;
    return true;
  };
  const uint8_t *uid, *msg, *patch, *reason;
  if (!take(uid_len, uid) || !take(msg_len, msg) ||
      !take(patch_len, patch) || !take(reason_len, reason))
    return false;
  // variable tails parsed in layout order BEFORE building (the body
  // interleaves them differently than the wire does). Every
  // caller-supplied length/count is bounds-checked against the buffer
  // BEFORE any allocation or pointer math — httpfront_render_verdict
  // is exported for arbitrary test/fuzz input and must answer
  // malformed records with false, never a crash (a u32 warning length
  // >= 2^31 must not wrap into take()'s signed "absent" sentinel, and
  // an n_causes count must not drive a giant reserve()).
  std::vector<std::pair<int64_t, const uint8_t*>> warns;
  warns.reserve(n_warn);
  for (uint8_t wi = 0; wi < n_warn; wi++) {
    if (off + 4 > len) return false;
    uint32_t wlen;
    memcpy(&wlen, buf + off, 4);
    off += 4;
    if ((int64_t)wlen > len - off) return false;
    warns.emplace_back((int64_t)wlen, buf + off);
    off += (int64_t)wlen;
  }
  std::vector<std::array<std::pair<int32_t, const uint8_t*>, 2>> causes;
  if (n_causes > 0) {
    if ((int64_t)n_causes * 8 > len - off) return false;  // 8 B/cause min
    causes.reserve((size_t)n_causes);
  }
  for (int32_t ci = 0; ci < n_causes; ci++) {
    if (off + 8 > len) return false;
    int32_t flen, mlen;
    memcpy(&flen, buf + off, 4);
    memcpy(&mlen, buf + off + 4, 4);
    off += 8;
    const uint8_t *fld, *cmsg;
    if (!take(flen, fld) || !take(mlen, cmsg)) return false;
    causes.push_back({std::make_pair(flen, fld), std::make_pair(mlen, cmsg)});
  }
  std::string resp;
  resp.reserve(160 + (size_t)uid_len + (size_t)(msg_len > 0 ? msg_len : 0) +
               (size_t)(patch_len > 0 ? patch_len : 0));
  resp += "{\"uid\": ";
  py_escape(std::string((const char*)uid, (size_t)uid_len), resp);
  resp += ", \"allowed\": ";
  resp += allowed ? "true" : "false";
  if (patch_len >= 0) {
    resp += ", \"patchType\": \"JSONPatch\", \"patch\": ";
    py_escape(std::string((const char*)patch, (size_t)patch_len), resp);
  }
  if (flags & 1) {
    resp += ", \"status\": {";
    bool first = true;
    auto sep = [&]() {
      if (!first) resp += ", ";
      first = false;
    };
    if (msg_len >= 0) {
      sep();
      resp += "\"message\": ";
      py_escape(std::string((const char*)msg, (size_t)msg_len), resp);
    }
    if (code >= 0) {
      sep();
      char tmp[24];
      snprintf(tmp, sizeof(tmp), "\"code\": %d", code);
      resp += tmp;
    }
    if (reason_len >= 0) {
      sep();
      resp += "\"reason\": ";
      py_escape(std::string((const char*)reason, (size_t)reason_len), resp);
    }
    if (n_causes >= 0) {
      sep();
      resp += "\"details\": {\"causes\": [";
      for (size_t ci = 0; ci < causes.size(); ci++) {
        if (ci) resp += ", ";
        resp += "{";
        int32_t flen = causes[ci][0].first, mlen = causes[ci][1].first;
        if (flen >= 0) {
          resp += "\"field\": ";
          py_escape(
              std::string((const char*)causes[ci][0].second, (size_t)flen),
              resp);
        }
        if (mlen >= 0) {
          if (flen >= 0) resp += ", ";
          resp += "\"message\": ";
          py_escape(
              std::string((const char*)causes[ci][1].second, (size_t)mlen),
              resp);
        }
        resp += "}";
      }
      resp += "]}";
    }
    resp += "}";
  }
  if (flags & 2) {
    resp += ", \"warnings\": [";
    for (size_t wi = 0; wi < warns.size(); wi++) {
      if (wi) resp += ", ";
      py_escape(std::string((const char*)warns[wi].second,
                            (size_t)warns[wi].first),
                resp);
    }
    resp += "]";
  }
  resp += "}";
  if (raw_shape)
    body = "{\"response\": " + resp + "}";
  else
    body =
        "{\"apiVersion\": \"admission.k8s.io/v1\", \"kind\": "
        "\"AdmissionReview\", \"response\": " +
        resp + "}";
  return true;
}

extern "C" {

// listen_fd: a bound+listening non-blocking socket the CALLER owns (Python
// creates it with SO_REUSEPORT so prefork processes can share the port).
void* httpfront_create(int listen_fd, int n_loops, int64_t max_body,
                       const char* server_hdr, int ring_bits) {
  if (n_loops < 1) n_loops = 1;
  if (n_loops > 64) n_loops = 64;
  if (ring_bits < 8) ring_bits = 8;
  if (ring_bits > 16) ring_bits = 16;
  Front* f = new Front();
  f->listen_fd = listen_fd;
  f->n_loops = n_loops;
  f->max_body = max_body;
  f->server_hdr = server_hdr ? server_hdr : "policy-server-tpu";
  f->sub_efd = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
  if (f->sub_efd < 0) {
    delete f;
    return nullptr;
  }
  for (int i = 0; i < n_loops; i++) {
    auto lp = std::make_unique<Loop>((size_t)ring_bits);
    lp->front = f;
    lp->idx = i;
    lp->ep = epoll_create1(EPOLL_CLOEXEC);
    lp->comp_efd = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
    if (lp->ep < 0 || lp->comp_efd < 0) {
      if (lp->ep >= 0) close(lp->ep);
      if (lp->comp_efd >= 0) close(lp->comp_efd);
      close(f->sub_efd);
      delete f;
      return nullptr;
    }
    f->loops.push_back(std::move(lp));
  }
  return f;
}

// Connection-abuse hardening knobs (0 disables each): idle keep-alive
// timeout, per-request read (header+body arrival) timeout, and the max
// concurrent connection cap (over-cap accepts answer an in-band 503 and
// close, counted). Callable before start() or live — the loops read the
// atomics on every sweep/accept.
void httpfront_configure(void* h, int64_t idle_timeout_ms,
                         int64_t read_timeout_ms, int64_t max_conns) {
  Front* f = (Front*)h;
  f->idle_timeout_ns.store(
      idle_timeout_ms > 0 ? idle_timeout_ms * 1000000ll : 0,
      std::memory_order_relaxed);
  f->read_timeout_ns.store(
      read_timeout_ms > 0 ? read_timeout_ms * 1000000ll : 0,
      std::memory_order_relaxed);
  f->max_conns.store(max_conns > 0 ? max_conns : 0,
                     std::memory_order_relaxed);
}

void httpfront_set_static(void* h, int slot, int status,
                          const char* content_type, const char* body,
                          int64_t body_len, const char* extra_headers) {
  Front* f = (Front*)h;
  if (slot < 0 || slot >= ST_MAX) return;
  StaticResp& st = f->statics[slot];
  st.status = status;
  st.content_type = content_type ? content_type : "text/plain; charset=utf-8";
  st.body.assign(body ? body : "", body ? (size_t)body_len : 0);
  st.extra = extra_headers ? extra_headers : "";
}

int httpfront_start(void* h) {
  Front* f = (Front*)h;
  for (auto& lp : f->loops) {
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = f->listen_fd;
    if (epoll_ctl(lp->ep, EPOLL_CTL_ADD, f->listen_fd, &ev) != 0) return -1;
    lp->listen_registered = true;
    ev = epoll_event{};
    ev.events = EPOLLIN;
    ev.data.fd = lp->comp_efd;
    if (epoll_ctl(lp->ep, EPOLL_CTL_ADD, lp->comp_efd, &ev) != 0) return -1;
  }
  for (auto& lp : f->loops) {
    Loop* raw = lp.get();
    lp->thr = std::thread([raw] { loop_main(raw); });
  }
  return 0;
}

void httpfront_stop_accepting(void* h) {
  Front* f = (Front*)h;
  f->stop_accepting.store(true, std::memory_order_relaxed);
  for (auto& lp : f->loops) wake_fd(lp->comp_efd);
}

void httpfront_stop(void* h) {
  Front* f = (Front*)h;
  f->stop.store(true, std::memory_order_release);
  for (auto& lp : f->loops) wake_fd(lp->comp_efd);
  wake_fd(f->sub_efd);
  for (auto& lp : f->loops)
    if (lp->thr.joinable()) lp->thr.join();
}

void httpfront_destroy(void* h) {
  Front* f = (Front*)h;
  for (auto& lp : f->loops) {
    // free undrained submission records and unprocessed completions
    for (uint8_t* rec = lp->ring.pop(); rec != nullptr; rec = lp->ring.pop())
      free(rec);
    Comp* c = lp->comps.take_all_reversed();
    while (c != nullptr) {
      Comp* nx = c->next;
      delete c;
      c = nx;
    }
    close(lp->ep);
    close(lp->comp_efd);
  }
  close(f->sub_efd);
  {
    std::lock_guard<std::mutex> g(f->tls_mu);
    if (f->tls_current != nullptr) {
      tls_ctx_unref(f->tls_current);
      f->tls_current = nullptr;
    }
  }
  delete f;
}

// Drain parsed requests into `buf` (concatenated records, each prefixed by
// its u32 total_len). Blocks up to timeout_ms when nothing is pending.
// Returns bytes written, 0 on timeout, -1 once stopped AND fully drained.
// graftcheck: wire-input
int64_t httpfront_poll(void* h, uint8_t* buf, int64_t cap, int timeout_ms) {
  Front* f = (Front*)h;
  int64_t deadline = now_ns() + (int64_t)timeout_ms * 1000000ll;
  for (;;) {
    uint64_t v;
    ssize_t r = read(f->sub_efd, &v, sizeof(v));  // stop()-wake drain
    (void)r;
    int64_t written = 0;
    for (auto& lp : f->loops) {
      for (;;) {
        uint8_t* rec = lp->ring.peek();
        if (rec == nullptr) break;
        uint32_t len;
        memcpy(&len, rec, sizeof(len));
        if ((int64_t)len > cap) {
          // defense-in-depth: a record the poll buffer can never hold
          // (submit_request's fallback bound should make this
          // unreachable) must not wedge the ring head forever — drop
          // it and answer the request in-band
          uint64_t req_id;
          memcpy(&req_id, rec + 4, sizeof(req_id));
          lp->ring.advance();
          free(rec);
          push_comp(f, req_id, 500,
                    0, "{\"message\": \"Something went wrong\", "
                       "\"status\": 500}");
          continue;
        }
        if (written + (int64_t)len > cap) break;
        memcpy(buf + written, rec, len);
        written += len;
        lp->ring.advance();
        free(rec);
      }
      if (written >= cap) break;
    }
    if (written > 0) return written;
    if (f->stop.load(std::memory_order_acquire)) return -1;
    if (now_ns() >= deadline) return 0;
    // producers do NOT wake the efd per request (syscalls are expensive
    // on sandboxed kernels): sleep one tick and re-scan. The efd only
    // carries the stop() wake, which cuts the final tick short.
    pollfd pfd{f->sub_efd, POLLIN, 0};
    (void)poll(&pfd, 1, 1);
  }
}

// Complete with a Python-rendered JSON body (error paths, mutations,
// warnings — anything the native serializer does not cover).
void httpfront_complete(void* h, uint64_t req_id, int status,
                        const uint8_t* body, int64_t body_len,
                        int retry_after) {
  Front* f = (Front*)h;
  f->stats[S_PY_SER].fetch_add(1, std::memory_order_relaxed);
  push_comp(f, req_id, status, retry_after,
            std::string((const char*)body, (size_t)body_len));
}

// Batch-granular completion fill (round 12; v2 records round 19): one
// call per dispatched batch. `buf` is the packed little-endian record
// sequence documented at parse_verdict_record — the Python side builds
// it once per batch and pays ONE ctypes crossing + ONE frontend lock
// instead of one per request, and the full response shape (patches,
// warnings, status reason/details tables) renders natively.
// graftcheck: wire-input
void httpfront_complete_verdict_bulk(void* h, const uint8_t* buf,
                                     int64_t len, int64_t count) {
  Front* f = (Front*)h;
  int64_t t0 = now_ns();
  int64_t off = 0;
  int64_t done = 0;
  uint64_t req_id;
  std::string body;
  while (done < count) {
    if (!parse_verdict_record(buf, len, off, req_id, body)) break;
    push_comp(f, req_id, 200, 0, std::move(body));
    done++;
  }
  f->stats[S_NATIVE_SER].fetch_add(done, std::memory_order_relaxed);
  f->stats[S_FRAMING_NS].fetch_add(now_ns() - t0, std::memory_order_relaxed);
}

// Differential-corpus export (tests/test_native_assembly.py): render ONE
// v2 verdict record's response body into `out` without touching any
// connection state. Returns the body length, or -1 on malformed input /
// insufficient capacity. This is the SAME parse+emit path serving uses,
// so the byte-exactness the corpus proves is the byte-exactness
// production emits.
// graftcheck: wire-input
int64_t httpfront_render_verdict(const uint8_t* buf, int64_t len,
                                 uint8_t* out, int64_t cap) {
  int64_t off = 0;
  uint64_t rid;
  std::string body;
  if (!parse_verdict_record(buf, len, off, rid, body)) return -1;
  if ((int64_t)body.size() > cap) return -1;
  memcpy(out, body.data(), body.size());
  return (int64_t)body.size();
}

int64_t httpfront_outstanding(void* h) {
  return ((Front*)h)->stats[S_OUTSTANDING].load(std::memory_order_relaxed);
}

// ------------------------------------------------------------- TLS C ABI --

// 1 when the dlopen'd OpenSSL binding resolved completely; 0 otherwise
// (httpfront_tls_error says why). The Python caller uses a 0 to degrade
// LOUDLY to the aiohttp TLS frontend — never to serve plaintext.
int httpfront_tls_available(void) { return tls_api()->ok ? 1 : 0; }

const char* httpfront_tls_error(void) {
  if (!tls_api()->ok) return tls_api()->err.c_str();
  return g_tls_err;
}

// Build one SSL_CTX generation from PEM bytes (certs.py's last-good
// identity snapshot): cert_pem may carry leaf+chain; a non-empty ca_pem
// turns on mTLS with CPython's CERT_REQUIRED semantics (verify peer,
// fail the handshake without a client cert; no CA-name hints — the
// ssl.SSLContext oracle sends none either, keeping handshake
// transcripts comparable). Returns an opaque refcounted handle or null
// with httpfront_tls_error set.
void* httpfront_tls_ctx_create(const uint8_t* cert_pem, int64_t cert_len,
                               const uint8_t* key_pem, int64_t key_len,
                               const uint8_t* ca_pem, int64_t ca_len) {
  TlsApi* a = tls_api();
  if (!a->ok) {
    snprintf(g_tls_err, sizeof(g_tls_err), "%s", a->err.c_str());
    return nullptr;
  }
  a->ERR_clear_error();
  void* ctx = a->SSL_CTX_new(a->TLS_server_method());
  if (ctx == nullptr) {
    set_tls_err("SSL_CTX_new failed");
    return nullptr;
  }
  // TLS 1.2 floor, matching ssl.SSLContext's webhook posture
  a->SSL_CTX_ctrl(ctx, kSSL_CTRL_SET_MIN_PROTO_VERSION, kTLS1_2_VERSION,
                  nullptr);
  bool ok = true;
  void* bio = a->BIO_new_mem_buf(cert_pem, (int)cert_len);
  void* leaf =
      bio != nullptr ? a->PEM_read_bio_X509(bio, nullptr, nullptr, nullptr)
                     : nullptr;
  if (leaf == nullptr) {
    set_tls_err("identity PEM holds no certificate");
    ok = false;
  } else {
    if (a->SSL_CTX_use_certificate(ctx, leaf) != 1) {
      set_tls_err("SSL_CTX_use_certificate failed");
      ok = false;
    }
    a->X509_free(leaf);
    while (ok) {  // remaining PEM blocks are the chain, ctx takes them
      void* extra = a->PEM_read_bio_X509(bio, nullptr, nullptr, nullptr);
      if (extra == nullptr) {
        a->ERR_clear_error();  // expected end-of-PEM parse error
        break;
      }
      if (a->SSL_CTX_ctrl(ctx, kSSL_CTRL_EXTRA_CHAIN_CERT, 0, extra) != 1) {
        a->X509_free(extra);
        set_tls_err("SSL_CTX add chain cert failed");
        ok = false;
      }
    }
  }
  if (bio != nullptr) a->BIO_free(bio);
  if (ok) {
    bio = a->BIO_new_mem_buf(key_pem, (int)key_len);
    void* pkey =
        bio != nullptr
            ? a->PEM_read_bio_PrivateKey(bio, nullptr, nullptr, nullptr)
            : nullptr;
    if (pkey == nullptr) {
      set_tls_err("identity PEM holds no private key");
      ok = false;
    } else {
      if (a->SSL_CTX_use_PrivateKey(ctx, pkey) != 1 ||
          a->SSL_CTX_check_private_key(ctx) != 1) {
        set_tls_err("private key does not match certificate");
        ok = false;
      }
      a->EVP_PKEY_free(pkey);
    }
    if (bio != nullptr) a->BIO_free(bio);
  }
  if (ok && ca_pem != nullptr && ca_len > 0) {
    void* store = a->SSL_CTX_get_cert_store(ctx);
    bio = a->BIO_new_mem_buf(ca_pem, (int)ca_len);
    int added = 0;
    for (;;) {
      void* x = bio != nullptr
                    ? a->PEM_read_bio_X509(bio, nullptr, nullptr, nullptr)
                    : nullptr;
      if (x == nullptr) {
        a->ERR_clear_error();
        break;
      }
      if (a->X509_STORE_add_cert(store, x) == 1) added++;
      a->X509_free(x);
    }
    if (bio != nullptr) a->BIO_free(bio);
    if (added == 0) {
      set_tls_err("client-CA PEM holds no certificate");
      ok = false;
    } else {
      a->SSL_CTX_set_verify(
          ctx, kSSL_VERIFY_PEER | kSSL_VERIFY_FAIL_IF_NO_PEER_CERT,
          nullptr);
    }
  }
  if (!ok) {
    a->SSL_CTX_free(ctx);
    return nullptr;
  }
  TlsCtx* t = new TlsCtx();
  t->ctx = ctx;
  return t;
}

void httpfront_tls_ctx_free(void* tctx) { tls_ctx_unref((TlsCtx*)tctx); }

// Atomically swap the generation NEW accepts handshake under; takes its
// own reference (the caller's handle stays valid until tls_ctx_free).
// Established connections keep draining on the generation they pinned
// at accept — hot rotation never cuts a live session. Null disables TLS
// for new connections.
void httpfront_set_tls(void* h, void* tctx) {
  Front* f = (Front*)h;
  TlsCtx* t = (TlsCtx*)tctx;
  if (t != nullptr) t->refs.fetch_add(1, std::memory_order_relaxed);
  TlsCtx* old = nullptr;
  {
    std::lock_guard<std::mutex> g(f->tls_mu);
    old = f->tls_current;
    f->tls_current = t;
  }
  if (old != nullptr) tls_ctx_unref(old);
}

// Handshake-arrival deadline (ms; 0 disables): measured from ACCEPT,
// never refreshed by arriving bytes — the TLS-layer slowloris clock.
void httpfront_tls_configure(void* h, int64_t handshake_timeout_ms) {
  Front* f = (Front*)h;
  f->tls_handshake_timeout_ns.store(
      handshake_timeout_ms > 0 ? handshake_timeout_ms * 1000000ll : 0,
      std::memory_order_relaxed);
}

// `tls.handshake` failpoint backend: fail the next n handshakes (n>0),
// every handshake (-1), or disarm (0). Failures are torn down before
// any handshake progress and counted under tls_fail_injected.
void httpfront_tls_fail_handshakes(void* h, long n) {
  ((Front*)h)->tls_fail_next.store(n, std::memory_order_relaxed);
}

// Capability probe for kTLS offload after the userspace handshake: the
// loaded OpenSSL must be a 3.x kTLS build (SSL_sendfile present).
// Against 1.1 this answers 0 and the Python side LOGS the probe result
// — an explicit no, never a silent downgrade.
int httpfront_ktls_supported(void) {
  TlsApi* a = tls_api();
  return (a->ok && a->ktls) ? 1 : 0;
}

void httpfront_stats(void* h, int64_t* out, int cap) {
  // cap is the caller's buffer size: the Python side allocates it from
  // its own constant, so a future STAT_N bump here must never write
  // past what the caller actually handed us
  Front* f = (Front*)h;
  int n = cap < STAT_N ? cap : STAT_N;
  for (int i = 0; i < n; i++)
    out[i] = f->stats[i].load(std::memory_order_relaxed);
}

}  // extern "C"
