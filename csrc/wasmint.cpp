// Native execution core for the wasm interpreter (wasm/interp.py).
//
// The Python interpreter stays the semantic reference and fallback; this
// file ports ONLY the hot dispatch loop. The module is decoded and
// validated in Python (wasm/binary.py), then translated into flat
// op/immediate arrays (wasm/native_exec.py) and executed here. Host
// imports (the waPC/OPA/WASI ABIs) call back into Python through a
// single dispatcher callback; linear memory lives here and Python reads
// and writes it through accessor functions.
//
// Semantics mirror interp.py operation for operation — including its
// Python-derived float min/max ordering, round-half-even "nearest", and
// trap messages — so the two engines stay drop-in interchangeable and
// differential-testable.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cmath>
#include <ctime>
#include <exception>
#include <new>
#include <vector>

namespace {

union Val {
    int64_t i;
    double f;
};

// status codes (mirrored in wasm/native_exec.py)
enum {
    OK = 0,
    TRAP = 1,
    FUEL = 2,
    DEADLINE = 3,
    HOSTERR = 4,
};

typedef int32_t (*HostCb)(void* ctx, int32_t fidx, const uint64_t* args,
                          int32_t nargs, uint64_t* results,
                          int32_t* nresults);

struct Func {
    int32_t type_id = 0;
    int32_t n_params = 0;
    int32_t n_results = 0;
    int32_t n_locals = 0;  // extra zero-initialised locals
    uint8_t is_host = 0;
    std::vector<uint32_t> ops;
    std::vector<int64_t> ia;
    std::vector<int32_t> ib;
    std::vector<int32_t> ic;
};

struct DataSeg {
    std::vector<uint8_t> bytes;
};

struct Module {
    std::vector<Func> funcs;
    std::vector<int32_t> br_pool;
    std::vector<DataSeg> data;
};

struct Ctrl {
    int32_t target_pc;
    int32_t height;
    int32_t arity;
    uint8_t is_loop;
};

struct Inst {
    Module* mod = nullptr;
    std::vector<uint8_t> mem;
    int64_t mem_max_pages = -1;  // -1: no declared maximum
    std::vector<Val> globals;
    std::vector<std::vector<int32_t>> tables;  // -1 = null element
    std::vector<uint8_t> data_dropped;
    int64_t fuel = 0;
    uint8_t has_fuel = 0;
    double deadline = 0.0;
    uint8_t has_deadline = 0;
    HostCb hostcb = nullptr;
    void* host_ctx = nullptr;
    int32_t depth = 0;
    int32_t err_code = OK;
    char err[512] = {0};
};

constexpr int64_t PAGE = 65536;
constexpr int32_t MAX_DEPTH = 1024;

int32_t trap(Inst* in, int32_t code, const char* msg) {
    in->err_code = code;
    snprintf(in->err, sizeof(in->err), "%s", msg);
    return code;
}

double mono_now() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (double)ts.tv_sec + (double)ts.tv_nsec * 1e-9;
}

inline int32_t I32(int64_t v) { return (int32_t)v; }
inline uint32_t U32(int64_t v) { return (uint32_t)v; }
inline uint64_t U64(int64_t v) { return (uint64_t)v; }
inline double F32(double v) { return (double)(float)v; }

// CPython min/max ordering (interp.py uses builtin min/max on floats):
// min(a, b) keeps a unless b < a; max keeps a unless b > a. NaN
// comparisons are false, so a NaN FIRST operand wins. Replicated so the
// engines agree bit-for-bit on NaN-bearing policies.
inline double pymin(double a, double b) { return (b < a) ? b : a; }
inline double pymax(double a, double b) { return (b > a) ? b : a; }

bool mem_ok(Inst* in, uint64_t addr, uint64_t n) {
    return addr + n <= in->mem.size() && addr + n >= addr;
}

int32_t exec_fn(Inst* in, const Func& fn, const Val* args, Val* results,
                int32_t* nresults);

int32_t call_index(Inst* in, int32_t findex, const Val* args, Val* results,
                   int32_t* nresults) {
    const Func& callee = in->mod->funcs[findex];
    if (callee.is_host) {
        uint64_t raw_args[32];
        uint64_t raw_res[32];
        int32_t nres = 0;
        for (int32_t i = 0; i < callee.n_params && i < 32; i++)
            memcpy(&raw_args[i], &args[i], 8);
        int32_t rc = in->hostcb(in->host_ctx, findex, raw_args,
                                callee.n_params, raw_res, &nres);
        if (rc != 0)
            return trap(in, HOSTERR, "host function raised");
        for (int32_t i = 0; i < nres && i < 32; i++)
            memcpy(&results[i], &raw_res[i], 8);
        *nresults = nres;
        return OK;
    }
    return exec_fn(in, callee, args, results, nresults);
}

// br mechanics, mirroring interp.py::_branch. Returns new pc in *npc, or
// sets *returned when the branch targets the function body.
void do_branch(int32_t label, std::vector<Ctrl>& ctrl, std::vector<Val>& stack,
               int32_t* npc, bool* returned) {
    if (label >= (int32_t)ctrl.size()) {
        *returned = true;
        return;
    }
    for (int32_t k = 0; k < label; k++) ctrl.pop_back();
    Ctrl c = ctrl.back();  // by value: the non-loop case pops it below
    int32_t arity = c.arity;
    // move `arity` results down to c.height
    for (int32_t k = 0; k < arity; k++)
        stack[c.height + k] = stack[stack.size() - arity + k];
    stack.resize(c.height + arity);
    if (c.is_loop) {
        *npc = c.target_pc + 1;  // continue after the loop header
    } else {
        ctrl.pop_back();
        *npc = c.target_pc + 1;  // continue after the matching END
    }
    *returned = false;
}

int32_t exec_fn(Inst* in, const Func& fn, const Val* args, Val* results,
                int32_t* nresults) {
    if (++in->depth > MAX_DEPTH) {
        in->depth--;
        return trap(in, TRAP, "call stack exhausted");
    }
    std::vector<Val> locals(fn.n_params + fn.n_locals);
    for (int32_t i = 0; i < fn.n_params; i++) locals[i] = args[i];
    for (int32_t i = fn.n_params; i < (int32_t)locals.size(); i++)
        locals[i].i = 0;
    std::vector<Val> stack;
    stack.reserve(64);
    std::vector<Ctrl> ctrl;
    ctrl.reserve(16);

    const uint32_t* ops = fn.ops.data();
    const int64_t* ia = fn.ia.data();
    const int32_t* ib = fn.ib.data();
    const int32_t* ic = fn.ic.data();
    int32_t pc = 0;

#define RET_RESULTS()                                                     \
    do {                                                                  \
        int32_t n = fn.n_results;                                         \
        for (int32_t k = 0; k < n; k++)                                   \
            results[k] = stack[stack.size() - n + k];                     \
        *nresults = n;                                                    \
        in->depth--;                                                      \
        return OK;                                                        \
    } while (0)
#define TRAPF(msg)                                                        \
    do {                                                                  \
        in->depth--;                                                      \
        return trap(in, TRAP, msg);                                       \
    } while (0)
#define POP() (stack.back().i);  // (unused helper removed)

    for (;;) {
        if (in->has_fuel) {
            in->fuel--;
            if (in->fuel <= 0) {
                in->fuel = 0;
                in->depth--;
                return trap(in, FUEL, "wasm fuel exhausted");
            }
            if (in->has_deadline && (in->fuel & 0xFFFF) == 0 &&
                mono_now() >= in->deadline) {
                in->depth--;
                return trap(in, DEADLINE, "wasm wall-clock deadline exceeded");
            }
        }
        uint32_t op = ops[pc];
        switch (op) {
            case 0x20:  // local.get
                stack.push_back(locals[ia[pc]]);
                break;
            case 0x21:  // local.set
                locals[ia[pc]] = stack.back();
                stack.pop_back();
                break;
            case 0x22:  // local.tee
                locals[ia[pc]] = stack.back();
                break;
            case 0x41:
            case 0x42: {  // i32/i64.const
                Val v;
                v.i = ia[pc];
                stack.push_back(v);
                break;
            }
            case 0x43:
            case 0x44: {  // f32/f64.const (double bits in ia)
                Val v;
                memcpy(&v.f, &ia[pc], 8);
                stack.push_back(v);
                break;
            }
            case 0x02: {  // block
                int32_t params = ib[pc];
                int32_t res = ic[pc];
                ctrl.push_back({(int32_t)ia[pc],
                                (int32_t)stack.size() - params, res, 0});
                break;
            }
            case 0x03: {  // loop
                int32_t params = ib[pc];
                ctrl.push_back({pc, (int32_t)stack.size() - params, params, 1});
                break;
            }
            case 0x04: {  // if: ia=end, ib=else(-1), ic=(params<<16)|results
                int32_t params = ic[pc] >> 16;
                int32_t res = ic[pc] & 0xFFFF;
                int64_t cond = stack.back().i;
                stack.pop_back();
                if (cond) {
                    ctrl.push_back({(int32_t)ia[pc],
                                    (int32_t)stack.size() - params, res, 0});
                } else if (ib[pc] >= 0) {
                    ctrl.push_back({(int32_t)ia[pc],
                                    (int32_t)stack.size() - params, res, 0});
                    pc = ib[pc];
                } else {
                    pc = (int32_t)ia[pc];  // past END; no frame pushed
                }
                break;
            }
            case 0x05:  // else (reached from then-branch): jump to end
                pc = (int32_t)ia[pc];
                ctrl.pop_back();
                break;
            case 0x0B:  // end
                if (!ctrl.empty()) {
                    ctrl.pop_back();
                } else {
                    RET_RESULTS();
                }
                break;
            case 0x0C: {  // br
                bool returned;
                int32_t npc;
                do_branch((int32_t)ia[pc], ctrl, stack, &npc, &returned);
                if (returned) RET_RESULTS();
                pc = npc;
                continue;
            }
            case 0x0D: {  // br_if
                int64_t cond = stack.back().i;
                stack.pop_back();
                if (cond) {
                    bool returned;
                    int32_t npc;
                    do_branch((int32_t)ia[pc], ctrl, stack, &npc, &returned);
                    if (returned) RET_RESULTS();
                    pc = npc;
                    continue;
                }
                break;
            }
            case 0x0E: {  // br_table: ia=pool start, ib=count
                uint32_t i = U32(stack.back().i);
                stack.pop_back();
                int32_t start = (int32_t)ia[pc];
                int32_t count = ib[pc];
                int32_t label = (i < (uint32_t)count)
                                    ? in->mod->br_pool[start + i]
                                    : in->mod->br_pool[start + count];
                bool returned;
                int32_t npc;
                do_branch(label, ctrl, stack, &npc, &returned);
                if (returned) RET_RESULTS();
                pc = npc;
                continue;
            }
            case 0x0F:  // return
                RET_RESULTS();
            case 0x10: {  // call
                int32_t findex = (int32_t)ia[pc];
                const Func& callee = in->mod->funcs[findex];
                int32_t n = callee.n_params;
                Val sub_args[32];
                for (int32_t k = 0; k < n; k++)
                    sub_args[k] = stack[stack.size() - n + k];
                stack.resize(stack.size() - n);
                Val sub_res[32];
                int32_t nres = 0;
                int32_t rc = call_index(in, findex, sub_args, sub_res, &nres);
                if (rc != OK) {
                    in->depth--;
                    return rc;
                }
                for (int32_t k = 0; k < nres; k++) stack.push_back(sub_res[k]);
                break;
            }
            case 0x11: {  // call_indirect: ia=type id, ib=table idx
                uint32_t elem = U32(stack.back().i);
                stack.pop_back();
                std::vector<int32_t>& table = in->tables[ib[pc]];
                if (elem >= table.size() || table[elem] < 0)
                    TRAPF("undefined element");
                int32_t findex = table[elem];
                const Func& callee = in->mod->funcs[findex];
                if (callee.type_id != (int32_t)ia[pc])
                    TRAPF("indirect call type mismatch");
                int32_t n = callee.n_params;
                Val sub_args[32];
                for (int32_t k = 0; k < n; k++)
                    sub_args[k] = stack[stack.size() - n + k];
                stack.resize(stack.size() - n);
                Val sub_res[32];
                int32_t nres = 0;
                int32_t rc = call_index(in, findex, sub_args, sub_res, &nres);
                if (rc != OK) {
                    in->depth--;
                    return rc;
                }
                for (int32_t k = 0; k < nres; k++) stack.push_back(sub_res[k]);
                break;
            }
            case 0x00:
                TRAPF("unreachable");
            case 0x01:  // nop
                break;
            case 0x1A:  // drop
                stack.pop_back();
                break;
            case 0x1B: {  // select
                Val c = stack.back();
                stack.pop_back();
                Val bv = stack.back();
                stack.pop_back();
                if (!c.i) stack.back() = bv;
                break;
            }
            case 0x23:  // global.get
                stack.push_back(in->globals[ia[pc]]);
                break;
            case 0x24:  // global.set
                in->globals[ia[pc]] = stack.back();
                stack.pop_back();
                break;

#define LOAD(nbytes, signedload, push64)                                      \
    {                                                                         \
        uint64_t addr = (uint64_t)U32(stack.back().i) + (uint64_t)ia[pc];     \
        stack.pop_back();                                                     \
        if (!mem_ok(in, addr, nbytes)) TRAPF("out of bounds memory access");  \
        uint64_t raw = 0;                                                     \
        memcpy(&raw, in->mem.data() + addr, nbytes);                          \
        int64_t out;                                                          \
        if (signedload) {                                                     \
            int shift = 64 - (nbytes)*8;                                      \
            out = ((int64_t)(raw << shift)) >> shift;                         \
        } else {                                                              \
            out = (int64_t)raw;                                               \
        }                                                                     \
        if (!(push64) && (signedload)) out = (int64_t)(int32_t)out;           \
        Val v;                                                                \
        v.i = out;                                                            \
        stack.push_back(v);                                                   \
    }

            case 0x28:  // i32.load (sign-extended into the slot, like _i32)
                LOAD(4, true, false);
                break;
            case 0x29:  // i64.load
                LOAD(8, true, true);
                break;
            case 0x2A: {  // f32.load
                uint64_t addr = (uint64_t)U32(stack.back().i) + (uint64_t)ia[pc];
                stack.pop_back();
                if (!mem_ok(in, addr, 4)) TRAPF("out of bounds memory access");
                float f;
                memcpy(&f, in->mem.data() + addr, 4);
                Val v;
                v.f = (double)f;
                stack.push_back(v);
                break;
            }
            case 0x2B: {  // f64.load
                uint64_t addr = (uint64_t)U32(stack.back().i) + (uint64_t)ia[pc];
                stack.pop_back();
                if (!mem_ok(in, addr, 8)) TRAPF("out of bounds memory access");
                Val v;
                memcpy(&v.f, in->mem.data() + addr, 8);
                stack.push_back(v);
                break;
            }
            case 0x2C:  // i32.load8_s
            case 0x30:  // i64.load8_s
                LOAD(1, true, true);
                break;
            case 0x2D:  // i32.load8_u
            case 0x31:  // i64.load8_u
                LOAD(1, false, true);
                break;
            case 0x2E:  // i32.load16_s
            case 0x32:  // i64.load16_s
                LOAD(2, true, true);
                break;
            case 0x2F:  // i32.load16_u
            case 0x33:  // i64.load16_u
                LOAD(2, false, true);
                break;
            case 0x34:  // i64.load32_s
                LOAD(4, true, true);
                break;
            case 0x35:  // i64.load32_u
                LOAD(4, false, true);
                break;

#define STORE(nbytes, maskexpr)                                               \
    {                                                                         \
        int64_t v = stack.back().i;                                           \
        stack.pop_back();                                                     \
        uint64_t addr = (uint64_t)U32(stack.back().i) + (uint64_t)ia[pc];     \
        stack.pop_back();                                                     \
        if (!mem_ok(in, addr, nbytes)) TRAPF("out of bounds memory access");  \
        uint64_t raw = (maskexpr);                                            \
        memcpy(in->mem.data() + addr, &raw, nbytes);                          \
    }

            case 0x36:  // i32.store
                STORE(4, (uint64_t)U32(v));
                break;
            case 0x37:  // i64.store
                STORE(8, U64(v));
                break;
            case 0x38: {  // f32.store
                double d = stack.back().f;
                stack.pop_back();
                uint64_t addr = (uint64_t)U32(stack.back().i) + (uint64_t)ia[pc];
                stack.pop_back();
                if (!mem_ok(in, addr, 4)) TRAPF("out of bounds memory access");
                float f = (float)d;
                memcpy(in->mem.data() + addr, &f, 4);
                break;
            }
            case 0x39: {  // f64.store
                double d = stack.back().f;
                stack.pop_back();
                uint64_t addr = (uint64_t)U32(stack.back().i) + (uint64_t)ia[pc];
                stack.pop_back();
                if (!mem_ok(in, addr, 8)) TRAPF("out of bounds memory access");
                memcpy(in->mem.data() + addr, &d, 8);
                break;
            }
            case 0x3A:  // i32.store8
            case 0x3C:  // i64.store8
                STORE(1, U64(v) & 0xFF);
                break;
            case 0x3B:  // i32.store16
            case 0x3D:  // i64.store16
                STORE(2, U64(v) & 0xFFFF);
                break;
            case 0x3E:  // i64.store32
                STORE(4, U64(v) & 0xFFFFFFFFull);
                break;
            case 0x3F: {  // memory.size
                Val v;
                v.i = (int64_t)(in->mem.size() / PAGE);
                stack.push_back(v);
                break;
            }
            case 0x40: {  // memory.grow
                int64_t delta = (int64_t)U32(stack.back().i);
                stack.pop_back();
                int64_t old_pages = (int64_t)(in->mem.size() / PAGE);
                int64_t new_pages = old_pages + delta;
                Val v;
                if ((in->mem_max_pages >= 0 && new_pages > in->mem_max_pages) ||
                    new_pages > 65536) {
                    v.i = -1;
                } else {
                    in->mem.resize((size_t)(new_pages * PAGE), 0);
                    v.i = old_pages;
                }
                stack.push_back(v);
                break;
            }

#define BINI(...)                                                             \
    {                                                                         \
        int64_t b = stack.back().i;                                           \
        stack.pop_back();                                                     \
        int64_t a = stack.back().i;                                           \
        int64_t r;                                                            \
        __VA_ARGS__;                                                          \
        stack.back().i = r;                                                   \
    }
#define CMP(...)                                                              \
    BINI({ r = (__VA_ARGS__) ? 1 : 0; })

            // i32 compare
            case 0x45: {  // i32.eqz
                stack.back().i = (stack.back().i == 0) ? 1 : 0;
                break;
            }
            case 0x46: CMP(U32(a) == U32(b)); break;
            case 0x47: CMP(U32(a) != U32(b)); break;
            case 0x48: CMP(I32(a) < I32(b)); break;
            case 0x49: CMP(U32(a) < U32(b)); break;
            case 0x4A: CMP(I32(a) > I32(b)); break;
            case 0x4B: CMP(U32(a) > U32(b)); break;
            case 0x4C: CMP(I32(a) <= I32(b)); break;
            case 0x4D: CMP(U32(a) <= U32(b)); break;
            case 0x4E: CMP(I32(a) >= I32(b)); break;
            case 0x4F: CMP(U32(a) >= U32(b)); break;
            // i64 compare
            case 0x50:
                stack.back().i = (stack.back().i == 0) ? 1 : 0;
                break;
            case 0x51: CMP(U64(a) == U64(b)); break;
            case 0x52: CMP(U64(a) != U64(b)); break;
            case 0x53: CMP(a < b); break;
            case 0x54: CMP(U64(a) < U64(b)); break;
            case 0x55: CMP(a > b); break;
            case 0x56: CMP(U64(a) > U64(b)); break;
            case 0x57: CMP(a <= b); break;
            case 0x58: CMP(U64(a) <= U64(b)); break;
            case 0x59: CMP(a >= b); break;
            case 0x5A: CMP(U64(a) >= U64(b)); break;

#define FCMP(expr)                                                            \
    {                                                                         \
        double b = stack.back().f;                                            \
        stack.pop_back();                                                     \
        double a = stack.back().f;                                            \
        stack.back().i = (expr) ? 1 : 0;                                      \
    }
            case 0x5B: case 0x61: FCMP(a == b); break;
            case 0x5C: case 0x62: FCMP(a != b); break;
            case 0x5D: case 0x63: FCMP(a < b); break;
            case 0x5E: case 0x64: FCMP(a > b); break;
            case 0x5F: case 0x65: FCMP(a <= b); break;
            case 0x60: case 0x66: FCMP(a >= b); break;

            // i32 arithmetic
            case 0x67: {  // i32.clz
                uint32_t v = U32(stack.back().i);
                stack.back().i = v == 0 ? 32 : __builtin_clz(v);
                break;
            }
            case 0x68: {  // i32.ctz
                uint32_t v = U32(stack.back().i);
                stack.back().i = v == 0 ? 32 : __builtin_ctz(v);
                break;
            }
            case 0x69:
                stack.back().i = __builtin_popcount(U32(stack.back().i));
                break;
            case 0x6A: BINI({ r = (int64_t)(int32_t)(U32(a) + U32(b)); }); break;
            case 0x6B: BINI({ r = (int64_t)(int32_t)(U32(a) - U32(b)); }); break;
            case 0x6C: BINI({ r = (int64_t)(int32_t)(U32(a) * U32(b)); }); break;
            case 0x6D:
                BINI({
                    int32_t x = I32(a), y = I32(b);
                    if (y == 0) TRAPF("integer divide by zero");
                    if (x == INT32_MIN && y == -1) TRAPF("integer overflow");
                    r = (int64_t)(x / y);
                });
                break;
            case 0x6E:
                BINI({
                    uint32_t x = U32(a), y = U32(b);
                    if (y == 0) TRAPF("integer divide by zero");
                    r = (int64_t)(int32_t)(x / y);
                });
                break;
            case 0x6F:
                BINI({
                    int32_t x = I32(a), y = I32(b);
                    if (y == 0) TRAPF("integer divide by zero");
                    r = (y == -1) ? 0 : (int64_t)(x % y);
                });
                break;
            case 0x70:
                BINI({
                    uint32_t x = U32(a), y = U32(b);
                    if (y == 0) TRAPF("integer divide by zero");
                    r = (int64_t)(int32_t)(x % y);
                });
                break;
            case 0x71: BINI({ r = (int64_t)(int32_t)(U32(a) & U32(b)); }); break;
            case 0x72: BINI({ r = (int64_t)(int32_t)(U32(a) | U32(b)); }); break;
            case 0x73: BINI({ r = (int64_t)(int32_t)(U32(a) ^ U32(b)); }); break;
            case 0x74:
                BINI({ r = (int64_t)(int32_t)(U32(a) << (b & 31)); });
                break;
            case 0x75: BINI({ r = (int64_t)(I32(a) >> (b & 31)); }); break;
            case 0x76:
                BINI({ r = (int64_t)(int32_t)(U32(a) >> (b & 31)); });
                break;
            case 0x77:
                BINI({
                    uint32_t s = (uint32_t)(b & 31), x = U32(a);
                    r = (int64_t)(int32_t)(s ? ((x << s) | (x >> (32 - s))) : x);
                });
                break;
            case 0x78:
                BINI({
                    uint32_t s = (uint32_t)(b & 31), x = U32(a);
                    r = (int64_t)(int32_t)(s ? ((x >> s) | (x << (32 - s))) : x);
                });
                break;
            // i64 arithmetic
            case 0x79: {
                uint64_t v = U64(stack.back().i);
                stack.back().i = v == 0 ? 64 : __builtin_clzll(v);
                break;
            }
            case 0x7A: {
                uint64_t v = U64(stack.back().i);
                stack.back().i = v == 0 ? 64 : __builtin_ctzll(v);
                break;
            }
            case 0x7B:
                stack.back().i = __builtin_popcountll(U64(stack.back().i));
                break;
            case 0x7C: BINI({ r = (int64_t)(U64(a) + U64(b)); }); break;
            case 0x7D: BINI({ r = (int64_t)(U64(a) - U64(b)); }); break;
            case 0x7E: BINI({ r = (int64_t)(U64(a) * U64(b)); }); break;
            case 0x7F:
                BINI({
                    if (b == 0) TRAPF("integer divide by zero");
                    if (a == INT64_MIN && b == -1) TRAPF("integer overflow");
                    r = a / b;
                });
                break;
            case 0x80:
                BINI({
                    if (b == 0) TRAPF("integer divide by zero");
                    r = (int64_t)(U64(a) / U64(b));
                });
                break;
            case 0x81:
                BINI({
                    if (b == 0) TRAPF("integer divide by zero");
                    r = (b == -1) ? 0 : a % b;
                });
                break;
            case 0x82:
                BINI({
                    if (b == 0) TRAPF("integer divide by zero");
                    r = (int64_t)(U64(a) % U64(b));
                });
                break;
            case 0x83: BINI({ r = a & b; }); break;
            case 0x84: BINI({ r = a | b; }); break;
            case 0x85: BINI({ r = a ^ b; }); break;
            case 0x86: BINI({ r = (int64_t)(U64(a) << (b & 63)); }); break;
            case 0x87: BINI({ r = a >> (b & 63); }); break;
            case 0x88: BINI({ r = (int64_t)(U64(a) >> (b & 63)); }); break;
            case 0x89:
                BINI({
                    uint64_t s = (uint64_t)(b & 63), x = U64(a);
                    r = (int64_t)(s ? ((x << s) | (x >> (64 - s))) : x);
                });
                break;
            case 0x8A:
                BINI({
                    uint64_t s = (uint64_t)(b & 63), x = U64(a);
                    r = (int64_t)(s ? ((x >> s) | (x << (64 - s))) : x);
                });
                break;

            // float unary/binary (f32 ops round results through float)
            case 0x8B: case 0x99: stack.back().f = fabs(stack.back().f); break;
            case 0x8C: case 0x9A: stack.back().f = -stack.back().f; break;
            case 0x8D: stack.back().f = F32(ceil(stack.back().f)); break;
            case 0x9B: stack.back().f = ceil(stack.back().f); break;
            case 0x8E: stack.back().f = F32(floor(stack.back().f)); break;
            case 0x9C: stack.back().f = floor(stack.back().f); break;
            case 0x8F: stack.back().f = F32(trunc(stack.back().f)); break;
            case 0x9D: stack.back().f = trunc(stack.back().f); break;
            case 0x90:
            case 0x9E: {  // nearest (round half to even, via interp.py's math)
                double v = stack.back().f;
                double fl = floor(v);
                double d = v - fl;
                double n;
                if (d > 0.5) n = fl + 1;
                else if (d < 0.5) n = fl;
                else n = (fmod(fl, 2.0) == 0.0) ? fl : fl + 1;
                stack.back().f = (op == 0x90) ? F32(n) : n;
                break;
            }
            case 0x91: stack.back().f = F32(sqrt(stack.back().f)); break;
            case 0x9F: stack.back().f = sqrt(stack.back().f); break;

#define FBIN(expr, round32)                                                   \
    {                                                                         \
        double b = stack.back().f;                                            \
        stack.pop_back();                                                     \
        double a = stack.back().f;                                            \
        double r = (expr);                                                    \
        stack.back().f = (round32) ? F32(r) : r;                              \
        (void)a;                                                              \
        (void)b;                                                              \
    }
            case 0x92: FBIN(a + b, true); break;
            case 0x93: FBIN(a - b, true); break;
            case 0x94: FBIN(a * b, true); break;
            case 0x95: FBIN(a / b, true); break;
            case 0x96: FBIN(pymin(a, b), false); break;
            case 0x97: FBIN(pymax(a, b), false); break;
            case 0x98: FBIN(copysign(a, b), false); break;
            case 0xA0: FBIN(a + b, false); break;
            case 0xA1: FBIN(a - b, false); break;
            case 0xA2: FBIN(a * b, false); break;
            case 0xA3: FBIN(a / b, false); break;
            case 0xA4: FBIN(pymin(a, b), false); break;
            case 0xA5: FBIN(pymax(a, b), false); break;
            case 0xA6: FBIN(copysign(a, b), false); break;

            // conversions
            case 0xA7:  // i32.wrap_i64
                stack.back().i = (int64_t)(int32_t)stack.back().i;
                break;
            case 0xA8:
            case 0xAA: {  // i32.trunc_f*_s
                double v = stack.back().f;
                if (std::isnan(v) || std::isinf(v))
                    TRAPF("invalid conversion to integer");
                double t = trunc(v);
                if (t < -2147483648.0 || t > 2147483647.0)
                    TRAPF("integer overflow");
                stack.back().i = (int64_t)t;
                break;
            }
            case 0xA9:
            case 0xAB: {  // i32.trunc_f*_u
                double v = stack.back().f;
                if (std::isnan(v) || std::isinf(v))
                    TRAPF("invalid conversion to integer");
                double t = trunc(v);
                if (t < 0.0 || t > 4294967295.0) TRAPF("integer overflow");
                stack.back().i = (int64_t)(int32_t)(uint32_t)(uint64_t)t;
                break;
            }
            case 0xAC:  // i64.extend_i32_s
                stack.back().i = (int64_t)(int32_t)stack.back().i;
                break;
            case 0xAD:  // i64.extend_i32_u
                stack.back().i = (int64_t)(uint32_t)stack.back().i;
                break;
            case 0xAE:
            case 0xB0: {  // i64.trunc_f*_s
                double v = stack.back().f;
                if (std::isnan(v) || std::isinf(v))
                    TRAPF("invalid conversion to integer");
                double t = trunc(v);
                if (t < -9223372036854775808.0 || t >= 9223372036854775808.0)
                    TRAPF("integer overflow");
                stack.back().i = (int64_t)t;
                break;
            }
            case 0xAF:
            case 0xB1: {  // i64.trunc_f*_u
                double v = stack.back().f;
                if (std::isnan(v) || std::isinf(v))
                    TRAPF("invalid conversion to integer");
                double t = trunc(v);
                if (t < 0.0 || t >= 18446744073709551616.0)
                    TRAPF("integer overflow");
                stack.back().i = (int64_t)(uint64_t)t;
                break;
            }
            case 0xB2:
                stack.back().f = F32((double)stack.back().i);
                break;
            case 0xB3:
                stack.back().f = F32((double)(uint32_t)stack.back().i);
                break;
            case 0xB4:
                stack.back().f = F32((double)stack.back().i);
                break;
            case 0xB5:
                stack.back().f = F32((double)U64(stack.back().i));
                break;
            case 0xB6:  // f32.demote_f64
                stack.back().f = F32(stack.back().f);
                break;
            case 0xB7:
                stack.back().f = (double)stack.back().i;
                break;
            case 0xB8:
                stack.back().f = (double)(uint32_t)stack.back().i;
                break;
            case 0xB9:
                stack.back().f = (double)stack.back().i;
                break;
            case 0xBA:
                stack.back().f = (double)U64(stack.back().i);
                break;
            case 0xBB:  // f64.promote_f32 (slot already double)
                break;
            case 0xBC: {  // i32.reinterpret_f32
                float f = (float)stack.back().f;
                int32_t bits;
                memcpy(&bits, &f, 4);
                stack.back().i = (int64_t)bits;
                break;
            }
            case 0xBD: {  // i64.reinterpret_f64
                int64_t bits;
                memcpy(&bits, &stack.back().f, 8);
                stack.back().i = bits;
                break;
            }
            case 0xBE: {  // f32.reinterpret_i32
                uint32_t bits = U32(stack.back().i);
                float f;
                memcpy(&f, &bits, 4);
                stack.back().f = (double)f;
                break;
            }
            case 0xBF: {  // f64.reinterpret_i64
                uint64_t bits = U64(stack.back().i);
                memcpy(&stack.back().f, &bits, 8);
                break;
            }
            // sign extension
            case 0xC0:
            case 0xC2: {
                int64_t v = stack.back().i & 0xFF;
                stack.back().i = (v & 0x80) ? v - 256 : v;
                break;
            }
            case 0xC1:
            case 0xC3: {
                int64_t v = stack.back().i & 0xFFFF;
                stack.back().i = (v & 0x8000) ? v - 65536 : v;
                break;
            }
            case 0xC4:  // i64.extend32_s
                stack.back().i = (int64_t)(int32_t)stack.back().i;
                break;

            default:
                if (op >= 0xFC00) {
                    uint32_t sub = op & 0xFF;
                    if (sub <= 7) {  // saturating trunc
                        double v = stack.back().f;
                        bool issigned = (sub % 2) == 0;
                        bool to64 = sub >= 4;
                        int64_t out;
                        if (std::isnan(v)) {
                            out = 0;
                        } else {
                            double t = std::isinf(v) ? v : trunc(v);
                            if (to64) {
                                if (issigned) {
                                    if (t <= -9223372036854775808.0)
                                        out = INT64_MIN;
                                    else if (t >= 9223372036854775807.0)
                                        out = INT64_MAX;
                                    else
                                        out = (int64_t)t;
                                } else {
                                    if (t <= 0.0)
                                        out = 0;
                                    else if (t >= 18446744073709551615.0)
                                        out = (int64_t)UINT64_MAX;
                                    else
                                        out = (int64_t)(uint64_t)t;
                                }
                            } else {
                                if (issigned) {
                                    if (t <= -2147483648.0)
                                        out = INT32_MIN;
                                    else if (t >= 2147483647.0)
                                        out = INT32_MAX;
                                    else
                                        out = (int64_t)(int32_t)t;
                                } else {
                                    if (t <= 0.0)
                                        out = 0;
                                    else if (t >= 4294967295.0)
                                        out = (int64_t)(int32_t)UINT32_MAX;
                                    else
                                        out = (int64_t)(int32_t)(uint32_t)t;
                                }
                            }
                        }
                        stack.back().i = out;
                    } else if (sub == 8) {  // memory.init
                        uint32_t n = U32(stack.back().i);
                        stack.pop_back();
                        uint32_t src = U32(stack.back().i);
                        stack.pop_back();
                        uint32_t dst = U32(stack.back().i);
                        stack.pop_back();
                        int32_t seg = (int32_t)ia[pc];
                        if (in->data_dropped[seg] && n)
                            TRAPF("data segment dropped");
                        const DataSeg& ds = in->mod->data[seg];
                        if ((uint64_t)src + n > ds.bytes.size())
                            TRAPF("out of bounds memory.init");
                        if (!mem_ok(in, dst, n))
                            TRAPF("out of bounds memory access");
                        memcpy(in->mem.data() + dst, ds.bytes.data() + src, n);
                    } else if (sub == 9) {  // data.drop
                        in->data_dropped[(int32_t)ia[pc]] = 1;
                    } else if (sub == 10) {  // memory.copy
                        uint32_t n = U32(stack.back().i);
                        stack.pop_back();
                        uint32_t src = U32(stack.back().i);
                        stack.pop_back();
                        uint32_t dst = U32(stack.back().i);
                        stack.pop_back();
                        if (!mem_ok(in, src, n) || !mem_ok(in, dst, n))
                            TRAPF("out of bounds memory access");
                        memmove(in->mem.data() + dst, in->mem.data() + src, n);
                    } else if (sub == 11) {  // memory.fill
                        uint32_t n = U32(stack.back().i);
                        stack.pop_back();
                        uint8_t val = (uint8_t)(stack.back().i & 0xFF);
                        stack.pop_back();
                        uint32_t dst = U32(stack.back().i);
                        stack.pop_back();
                        if (!mem_ok(in, dst, n))
                            TRAPF("out of bounds memory access");
                        memset(in->mem.data() + dst, val, n);
                    } else {
                        TRAPF("unsupported extended op");
                    }
                } else {
                    TRAPF("unsupported opcode");
                }
        }
        pc += 1;
    }
}

}  // namespace

extern "C" {

// Every entry point that allocates is exception-guarded: C++ exceptions
// must never unwind across the ctypes boundary (undefined behavior; in
// practice std::terminate kills the whole server). Allocating void
// functions return an int32 status instead (0 ok, 1 allocation failure)
// so the bridge can raise per-request.

void* wasmint_module_new() {
    try {
        return new Module();
    } catch (...) {
        return nullptr;
    }
}

void wasmint_module_free(void* m) { delete (Module*)m; }

int32_t wasmint_add_func(void* m, int32_t type_id, int32_t n_params,
                         int32_t n_results, int32_t n_locals, int32_t is_host,
                         const uint32_t* ops, const int64_t* ia,
                         const int32_t* ib, const int32_t* ic, int64_t n) {
    try {
        Module* mod = (Module*)m;
        mod->funcs.emplace_back();
        Func& f = mod->funcs.back();
        f.type_id = type_id;
        f.n_params = n_params;
        f.n_results = n_results;
        f.n_locals = n_locals;
        f.is_host = (uint8_t)is_host;
        if (!is_host && n > 0) {
            f.ops.assign(ops, ops + n);
            f.ia.assign(ia, ia + n);
            f.ib.assign(ib, ib + n);
            f.ic.assign(ic, ic + n);
        }
        return 0;
    } catch (...) {
        return 1;
    }
}

int32_t wasmint_set_brpool(void* m, const int32_t* pool, int64_t n) {
    try {
        ((Module*)m)->br_pool.assign(pool, pool + n);
        return 0;
    } catch (...) {
        return 1;
    }
}

int32_t wasmint_add_data(void* m, const uint8_t* bytes, int64_t n) {
    try {
        Module* mod = (Module*)m;
        mod->data.emplace_back();
        mod->data.back().bytes.assign(bytes, bytes + n);
        return 0;
    } catch (...) {
        return 1;
    }
}

// C++ exceptions must not unwind across the ctypes boundary (undefined
// behavior; in practice std::terminate kills the whole server). A policy
// module can legally request a ~4 GiB initial memory, so allocation
// failure here is reachable from untrusted-but-verified input: report it
// as NULL and let the bridge raise a per-request trap instead.
void* wasmint_inst_new(void* m, int64_t mem_pages, int64_t mem_max_pages,
                       int64_t fuel, int32_t has_fuel, double deadline,
                       int32_t has_deadline, HostCb cb, void* ctx) {
    Module* mod = (Module*)m;
    Inst* in = nullptr;
    try {
        in = new Inst();
        in->mod = mod;
        in->mem.assign((size_t)(mem_pages * PAGE), 0);
        in->mem_max_pages = mem_max_pages;
        in->fuel = fuel;
        in->has_fuel = (uint8_t)has_fuel;
        in->deadline = deadline;
        in->has_deadline = (uint8_t)has_deadline;
        in->hostcb = cb;
        in->host_ctx = ctx;
        in->data_dropped.assign(mod->data.size(), 0);
        return in;
    } catch (...) {
        delete in;
        return nullptr;
    }
}

void wasmint_inst_free(void* i) { delete (Inst*)i; }

int32_t wasmint_set_globals(void* i, const uint64_t* bits, int64_t n) {
    try {
        Inst* in = (Inst*)i;
        in->globals.resize((size_t)n);
        for (int64_t k = 0; k < n; k++) memcpy(&in->globals[k], &bits[k], 8);
        return 0;
    } catch (...) {
        return 1;
    }
}

int64_t wasmint_get_global(void* i, int64_t idx) {
    Inst* in = (Inst*)i;
    int64_t out;
    memcpy(&out, &in->globals[(size_t)idx], 8);
    return out;
}

int32_t wasmint_add_table(void* i, const int32_t* elems, int64_t n) {
    try {
        Inst* in = (Inst*)i;
        in->tables.emplace_back(elems, elems + n);
        return 0;
    } catch (...) {
        return 1;
    }
}

int64_t wasmint_mem_size(void* i) {
    return (int64_t)(((Inst*)i)->mem.size());
}

int32_t wasmint_mem_read(void* i, int64_t addr, int64_t n, uint8_t* out) {
    Inst* in = (Inst*)i;
    if (addr < 0 || (uint64_t)(addr + n) > in->mem.size()) return 1;
    memcpy(out, in->mem.data() + addr, (size_t)n);
    return 0;
}

int32_t wasmint_mem_write(void* i, int64_t addr, const uint8_t* data,
                          int64_t n) {
    Inst* in = (Inst*)i;
    if (addr < 0 || (uint64_t)(addr + n) > in->mem.size()) return 1;
    memcpy(in->mem.data() + addr, data, (size_t)n);
    return 0;
}

// find the first NUL at/after addr; -1 when none (read_cstring support)
int64_t wasmint_mem_find0(void* i, int64_t addr) {
    Inst* in = (Inst*)i;
    if (addr < 0 || (uint64_t)addr >= in->mem.size()) return -1;
    const void* p = memchr(in->mem.data() + addr, 0, in->mem.size() - addr);
    if (p == nullptr) return -1;
    return (int64_t)((const uint8_t*)p - in->mem.data());
}

int64_t wasmint_fuel_left(void* i) { return ((Inst*)i)->fuel; }

void wasmint_set_fuel(void* i, int64_t fuel, int32_t has_fuel) {
    ((Inst*)i)->fuel = fuel;
    ((Inst*)i)->has_fuel = (uint8_t)has_fuel;
}

const char* wasmint_err(void* i) { return ((Inst*)i)->err; }

int32_t wasmint_invoke(void* i, int32_t findex, const uint64_t* args,
                       int32_t nargs, uint64_t* results,
                       int32_t* nresults) {
    Inst* in = (Inst*)i;
    in->err_code = OK;
    in->err[0] = 0;
    Val vargs[32];
    for (int32_t k = 0; k < nargs && k < 32; k++)
        memcpy(&vargs[k], &args[k], 8);
    Val vres[32];
    int32_t nres = 0;
    int32_t rc;
    // memory.grow and value-stack growth allocate mid-interpretation; a
    // thrown bad_alloc must become a per-request TRAP, never unwind into
    // ctypes (std::terminate would take the whole server down).
    try {
        rc = call_index(in, findex, vargs, vres, &nres);
    } catch (const std::bad_alloc&) {
        rc = trap(in, TRAP, "out of memory");
    } catch (const std::exception& e) {
        rc = trap(in, TRAP, e.what());
    } catch (...) {
        rc = trap(in, TRAP, "native engine exception");
    }
    if (rc != OK) return rc;
    for (int32_t k = 0; k < nres && k < 32; k++)
        memcpy(&results[k], &vres[k], 8);
    *nresults = nres;
    return OK;
}

}  // extern "C"
