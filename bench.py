"""Benchmark suite: the five BASELINE.md configs + the HTTP serving path.

Prints one JSON line per benchmark, the HEADLINE line LAST (config 4, the
32-policy firehose — the driver's recorded metric):

    {"metric": "...", "value": N, "unit": "...", "vs_baseline": N, ...}

``vs_baseline`` is value / 100_000 on throughput metrics — the north-star
target from BASELINE.json (the reference publishes no numbers; ≥1.0 means
the target is met on this hardware). Latency-only lines use the <10 ms
p99 target instead (vs_baseline = 10 / p99, ≥1.0 means met).

Configs (BASELINE.md:34-40):
1. namespace-validate — single policy, batch=1 (the CPU-reference shape);
2. psp-capabilities + psp-apparmor — 2 policies, 1k-request replay;
3. pod-image-signatures group — OR/AND expression tree over 3 members;
4. 32 mixed policies, synthetic firehose (headline);
5. multi-tenant 8-shard policy-sharded mesh incl. preemption churn — runs
   in a subprocess on the 8-virtual-device CPU mesh (multi-chip hardware
   is not present; the virtual mesh measures routing/rebalance overheads,
   clearly labeled);
plus HTTP lines driving the REAL server (aiohttp, concurrent clients)
through the micro-batcher: end-to-end p50/p95/p99 with median/min/max
spread over 3 timed waves, a latency-budget-router A/B at c64, and a
c256 overload run with load shedding on vs off (accepted-p99 + shed
rate).
"""

from __future__ import annotations

import json
import math
import os
import statistics
import subprocess
import sys
import time

NORTH_STAR_RPS = 100_000.0
NORTH_STAR_P99_MS = 10.0

# every emitted (metric, value, unit) — re-printed as one compact
# bench_summary line before the headline so a truncated tail window
# (BENCH_r04 lost config1-3) still records every number
_EMITTED: list[tuple[str, float, str]] = []


def pct(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = max(0, math.ceil(q * len(sorted_vals)) - 1)
    return sorted_vals[idx]


def emit(metric: str, value: float, unit: str, vs: float, **details) -> None:
    _EMITTED.append((metric, round(value, 2), unit))
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(value, 2),
                "unit": unit,
                "vs_baseline": round(vs, 4),
                "details": details,
            }
        ),
        flush=True,
    )


def spread(walls_to_rps: list[float]) -> dict:
    """median + min/max over N timed passes — the tunneled transport
    drifts ±40% between identical runs (VERDICT r4 weak #3), so a point
    value is not defensible against a same-day re-run."""
    vals = sorted(walls_to_rps)
    return {
        "median": statistics.median(vals),
        "min": vals[0],
        "max": vals[-1],
        "runs": [round(v, 1) for v in walls_to_rps],
    }


def build_requests(n: int, seed: int = 42):
    from policy_server_tpu.models import AdmissionReviewRequest, ValidateRequest
    from policy_server_tpu.policies.flagship import synthetic_firehose

    return [
        ValidateRequest.from_admission(
            AdmissionReviewRequest.from_dict(doc).request
        )
        for doc in synthetic_firehose(n, seed=seed)
    ]


def build_env(policies: dict):
    from policy_server_tpu.evaluation.environment import (
        EvaluationEnvironmentBuilder,
    )
    from policy_server_tpu.models.policy import parse_policy_entry

    return EvaluationEnvironmentBuilder(backend="jax").build(
        {k: parse_policy_entry(k, v) for k, v in policies.items()}
    )


# ---------------------------------------------------------------------------
# Config 1: namespace-validate, single request (batch=1)
# ---------------------------------------------------------------------------


def bench_config1(requests) -> None:
    """The webhook-like shape: one request at a time through the SERVING
    path (micro-batcher with the host latency fast-path). vs_baseline is
    against this config's own reference point — the reference's CPU sync
    path answers a single request in ≈1 ms (≈1k reviews/s) — not the
    100k/chip pod target, which is meaningless at batch=1."""
    from policy_server_tpu.api.service import RequestOrigin
    from policy_server_tpu.runtime.batcher import MicroBatcher

    ref_single_rps = 1_000.0  # reference CPU sync path, ≈1 ms/request
    env = build_env(
        {
            "namespace-validate": {
                "module": "builtin://namespace-validate",
                "settings": {"denied_namespaces": ["kube-system"]},
            }
        }
    )
    env.warmup((1,))
    batcher = MicroBatcher(
        env,
        max_batch_size=64,
        batch_timeout_ms=0.0,
        policy_timeout=30.0,
        host_fastpath_threshold=64,
    ).start()
    reqs = requests[:2048]
    try:
        for r in reqs[:8]:
            batcher.evaluate("namespace-validate", r, RequestOrigin.VALIDATE)
        lats = []
        t0 = time.perf_counter()
        for r in reqs:
            t1 = time.perf_counter()
            batcher.evaluate("namespace-validate", r, RequestOrigin.VALIDATE)
            lats.append((time.perf_counter() - t1) * 1e3)
        wall = time.perf_counter() - t0
    finally:
        batcher.shutdown()
    lats.sort()
    rps = len(reqs) / wall
    emit(
        "config1_namespace_validate_single",
        rps,
        "reviews/s",
        rps / ref_single_rps,
        p50_ms=round(pct(lats, 0.5), 2),
        p99_ms=round(pct(lats, 0.99), 2),
        batch_size=1,
        n_requests=len(reqs),
        host_fastpath_requests=env.host_fastpath_requests,
        baseline="reference CPU sync path ≈1k reviews/s (≈1 ms/request); "
        "vs_baseline is against that, not the 100k/chip pod target",
        note="serving path: micro-batcher + host latency fast-path",
    )


# ---------------------------------------------------------------------------
# Config 2: psp-capabilities + psp-apparmor, 1k replay
# ---------------------------------------------------------------------------


def bench_config2(requests) -> None:
    env = build_env(
        {
            "psp-capabilities": {
                "module": "builtin://psp-capabilities",
                "allowedToMutate": True,
                "settings": {
                    "allowed_capabilities": ["NET_BIND_SERVICE", "CHOWN"],
                    "required_drop_capabilities": ["NET_ADMIN"],
                    "default_add_capabilities": ["CHOWN"],
                },
            },
            "psp-apparmor": {
                "module": "builtin://psp-apparmor",
                "settings": {"allowed_profiles": ["runtime/default"]},
            },
        }
    )
    corpus = requests[:1000]
    items = [
        ("psp-capabilities" if i % 2 else "psp-apparmor", r)
        for i, r in enumerate(corpus)
    ]
    env.max_dispatch_batch = 512
    env.warmup((512,))
    env.validate_batch(items)  # prime
    rps_runs = []
    for _ in range(3):
        # reset before EVERY timed call: a second pass over the identical
        # replay would otherwise be answered from the verdict cache and
        # double-count as device throughput
        t0 = time.perf_counter()
        for _rep in range(2):
            env.reset_verdict_cache()
            env.validate_batch(items)
        rps_runs.append(2 * len(items) / (time.perf_counter() - t0))
    s = spread(rps_runs)
    emit(
        "config2_psp_pair_1k_replay",
        s["median"],
        "reviews/s/chip",
        s["median"] / NORTH_STAR_RPS,
        rps_min=round(s["min"], 1),
        rps_max=round(s["max"], 1),
        rps_runs=s["runs"],
        replay_size=len(items),
        n_policies=2,
    )


# ---------------------------------------------------------------------------
# Config 3: pod-image-signatures policy group (OR/AND tree)
# ---------------------------------------------------------------------------


def bench_config3(requests) -> None:
    from policy_server_tpu.policies.flagship import _signature_fixture

    store, pub = _signature_fixture()
    env = build_env(
        {
            "pod-image-signatures": {
                "expression": "signed() || (trusted() && not_latest())",
                "message": "image provenance cannot be established",
                "policies": {
                    "signed": {
                        "module": "builtin://verify-image-signatures",
                        "settings": {
                            "signatures": [
                                {
                                    "image": "registry.prod.example.com/*",
                                    "pubKeys": [pub],
                                }
                            ],
                            "signatureStore": store,
                        },
                    },
                    "trusted": {
                        "module": "builtin://trusted-repos",
                        "settings": {"registries": {"allow": ["docker.io"]}},
                    },
                    "not_latest": {"module": "builtin://disallow-latest-tag"},
                },
            }
        }
    )
    corpus = requests[:4096]
    items = [("pod-image-signatures", r) for r in corpus]
    env.max_dispatch_batch = 1024
    env.warmup((1024,))
    env.validate_batch(items)  # prime with a FULL pass (same buckets)
    rps_runs = []
    for _ in range(3):
        env.reset_verdict_cache()
        t0 = time.perf_counter()
        env.validate_batch(items)
        rps_runs.append(len(items) / (time.perf_counter() - t0))
    s = spread(rps_runs)
    emit(
        "config3_image_signatures_group",
        s["median"],
        "reviews/s/chip",
        s["median"] / NORTH_STAR_RPS,
        rps_min=round(s["min"], 1),
        rps_max=round(s["max"], 1),
        rps_runs=s["runs"],
        n_requests=len(items),
        group_members=3,
        expression="signed() || (trusted() && not_latest())",
    )


# ---------------------------------------------------------------------------
# Config 5: 8-shard multi-tenant + preemption churn (virtual CPU mesh)
# ---------------------------------------------------------------------------


def bench_config5_child() -> None:
    """Runs in a subprocess with JAX_PLATFORMS=cpu and 8 virtual devices."""
    import jax

    # the axon site package pins jax_platforms to the real TPU regardless
    # of JAX_PLATFORMS (see tests/conftest.py); override before backend init
    jax.config.update("jax_platforms", "cpu")

    from policy_server_tpu.config.config import MeshSpec
    from policy_server_tpu.parallel import PolicyShardedEvaluator, make_mesh
    from policy_server_tpu.models.policy import parse_policy_entry

    # 8 tenants × namespace fence + shared pod-security = 16 policies over
    # a policy:8 mesh (each shard data-parallel over 1 device)
    policies = {}
    for t in range(8):
        policies[f"tenant{t}-fence"] = parse_policy_entry(
            f"tenant{t}-fence",
            {
                "module": "builtin://namespace-validate",
                "settings": {"denied_namespaces": [f"tenant-{t}-restricted"]},
            },
        )
        policies[f"tenant{t}-priv"] = parse_policy_entry(
            f"tenant{t}-priv", {"module": "builtin://pod-privileged"}
        )
    mesh = make_mesh(MeshSpec.parse("data:1,policy:8"))
    sharded = PolicyShardedEvaluator(policies, mesh)
    requests = build_requests(2048, seed=9)
    pids = list(policies)
    items = [(pids[i % len(pids)], r) for i, r in enumerate(requests)]
    # prime with a FULL pass: per-shard batches land in the same shape
    # bucket as the timed run, so XLA compiles OUTSIDE the timed region
    # (priming with a slice measured compile time, not serving: 2,085
    # rps reported in r3 vs ~90k steady-state on the same machine)
    sharded.validate_batch(items)
    rps_runs = []
    for _ in range(3):
        for env in sharded.shards:
            env.reset_verdict_cache()
        t0 = time.perf_counter()
        sharded.validate_batch(items)
        rps_runs.append(len(items) / (time.perf_counter() - t0))
    rps_runs.sort()

    # preemption churn: drop 2 of 8 devices, measure the rebuild, and
    # verify serving continues
    t1 = time.perf_counter()
    sharded.resize(list(jax.devices())[:6])
    churn_s = time.perf_counter() - t1
    # first post-churn batch pays the rebalanced shards' compiles —
    # report that stall separately from steady-state serving
    t2 = time.perf_counter()
    sharded.validate_batch(items[:512])
    first_post_wall = time.perf_counter() - t2
    t3 = time.perf_counter()
    sharded.validate_batch(items[:512])
    post_wall = time.perf_counter() - t3

    print(
        json.dumps(
            {
                "rps": rps_runs[len(rps_runs) // 2],
                "rps_min": rps_runs[0],
                "rps_max": rps_runs[-1],
                "churn_rebuild_s": churn_s,
                "post_churn_first_batch_s": first_post_wall,
                "post_churn_rps": 512 / post_wall,
                "shards_before": 8,
                "shards_after": sharded.mesh.shape["policy"],
            }
        )
    )


def bench_config5() -> None:
    child_env = dict(os.environ)
    child_env.update(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS=(
            child_env.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip(),
    )
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--config5-child"],
        capture_output=True,
        text=True,
        env=child_env,
        timeout=1800,
        check=False,
    )
    line = out.stdout.strip().splitlines()[-1] if out.stdout.strip() else ""
    try:
        doc = json.loads(line)
    except (ValueError, IndexError):
        emit(
            "config5_multitenant_8shards_virtual",
            0.0,
            "reviews/s (8 virtual cpu devices)",
            0.0,
            error=(out.stderr or "no output")[-400:],
        )
        return
    emit(
        "config5_multitenant_8shards_virtual",
        doc["rps"],
        "reviews/s (8 virtual cpu devices)",
        doc["rps"] / NORTH_STAR_RPS,
        rps_min=round(doc.get("rps_min", doc["rps"]), 1),
        rps_max=round(doc.get("rps_max", doc["rps"]), 1),
        churn_rebuild_s=round(doc["churn_rebuild_s"], 2),
        post_churn_first_batch_s=round(doc["post_churn_first_batch_s"], 2),
        post_churn_rps=round(doc["post_churn_rps"], 1),
        shards_before=doc["shards_before"],
        shards_after=doc["shards_after"],
        note="virtual CPU mesh: multi-chip hardware not present; measures "
        "MPMD routing + churn rebuild, not TPU throughput",
    )


# ---------------------------------------------------------------------------
# HTTP serving path: real server, concurrent clients, p50/p99
# ---------------------------------------------------------------------------


def _decomp_snapshot(server) -> dict:
    """Cumulative per-stage counters for the framing/queue/device time
    decomposition (round-11 satellite): where a served request's wall
    time goes — native framing (C++ threads), batcher queue wait, host
    encode+bookkeeping, device wait."""
    bs = server.batcher.stats_snapshot()
    prof = dict(getattr(server.environment, "host_profile", {}) or {})
    nf = getattr(server, "_native_frontend", None)
    nstats = nf.stats() if nf is not None else {}
    return {
        "requests": bs["requests_dispatched"],
        "queue_wait_ns": bs["queue_wait_ns"],
        "encode_ns": prof.get("encode_ns", 0),
        "bookkeeping_ns": prof.get("bookkeeping_ns", 0),
        "device_wait_ns": prof.get("dispatch_wait_ns", 0),
        "framing_ns": nstats.get("framing_ns", 0),
        "parse_fallbacks": nstats.get("parse_fallbacks", 0),
    }


def _decompose(before: dict, after: dict) -> dict:
    """Per-request stage times between two snapshots. 'unattributed' is
    everything else — handler/runtime Python, GIL waits, and (for the
    Python frontend) the asyncio HTTP framing itself, which has no
    counter; on the native frontend framing is measured directly."""
    d = {k: after[k] - before[k] for k in before}
    n = max(1, d["requests"])
    return {
        "requests_dispatched": d["requests"],
        "framing_ms_per_req": round(d["framing_ns"] / 1e6 / n, 4),
        "queue_wait_ms_per_req": round(d["queue_wait_ns"] / 1e6 / n, 3),
        "host_encode_ms_per_req": round(d["encode_ns"] / 1e6 / n, 3),
        "host_bookkeeping_ms_per_req": round(
            d["bookkeeping_ns"] / 1e6 / n, 3
        ),
        "device_wait_ms_per_req": round(d["device_wait_ns"] / 1e6 / n, 3),
        "native_parse_fallbacks": d["parse_fallbacks"],
    }


def _http_bench_core(
    n_requests: int,
    concurrency: int,
    config_overrides: dict | None = None,
    waves: int = 3,
    allowed_statuses: tuple = (200,),
) -> dict:
    """Boot a REAL server, drive it with `concurrency` concurrent clients
    for `waves` timed passes over the same body set, return stats.

    Latency percentiles are computed over ACCEPTED (HTTP 200) responses
    only — under load shedding the 429s are the mechanism, and mixing
    their (fast) turnaround into the latency line would flatter it.
    Per-wave rps/p99 feed the spread the device lines already carry
    (round-7 satellite: VM weather and regressions were previously
    indistinguishable on HTTP lines)."""
    import asyncio
    import threading

    import aiohttp

    from policy_server_tpu.config.config import Config
    from policy_server_tpu.policies.flagship import (
        flagship_policies,
        synthetic_firehose,
    )
    from policy_server_tpu.server import PolicyServer

    cfg = dict(
        addr="127.0.0.1",
        port=0,
        readiness_probe_port=0,
        policies=flagship_policies(),
        max_batch_size=256,
        batch_timeout_ms=1.0,
        policy_timeout_seconds=30.0,  # bench must measure, not clip
    )
    cfg.update(config_overrides or {})
    server = PolicyServer.new_from_config(Config(**cfg))

    loop_box: dict = {}
    started = threading.Event()

    def run_server() -> None:
        loop = asyncio.new_event_loop()
        loop_box["loop"] = loop
        asyncio.set_event_loop(loop)

        async def main() -> None:
            await server.start()
            started.set()
            while not loop_box.get("stop"):
                await asyncio.sleep(0.05)
            await server.stop()

        loop.run_until_complete(main())

    t = threading.Thread(target=run_server, daemon=True)
    t.start()
    if not started.wait(timeout=600):
        raise RuntimeError("bench server failed to start")
    port = server.api_port

    docs = synthetic_firehose(n_requests, seed=77)
    bodies = [
        json.dumps(
            {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
             "request": d["request"]}
        ).encode()
        for d in docs
    ]
    url = f"http://127.0.0.1:{port}/validate/pod-security-group"
    lats: list[float] = []  # accepted (200) latencies, current wave
    statuses: dict[int, int] = {}
    wave_stats: list[dict] = []
    decomp_box: dict = {}

    async def client() -> None:
        connector = aiohttp.TCPConnector(limit=concurrency)
        async with aiohttp.ClientSession(connector=connector) as session:
            sem = asyncio.Semaphore(concurrency)

            async def one(body: bytes) -> None:
                async with sem:
                    t0 = time.perf_counter()
                    async with session.post(
                        url, data=body,
                        headers={"Content-Type": "application/json"},
                    ) as resp:
                        data = await resp.read()
                        assert resp.status in allowed_statuses, resp.status
                        key = resp.status
                        if resp.status == 200:
                            # overload answers travel IN-BAND: an expired
                            # or deadline-cut review is HTTP 200 with
                            # response.status.code 429/500/503/504 — only
                            # genuinely served verdicts may count toward
                            # the accepted latency line
                            code = None
                            try:
                                st = (
                                    json.loads(data)
                                    .get("response", {})
                                    .get("status")
                                ) or {}
                                code = st.get("code")
                            except (ValueError, AttributeError):
                                pass
                            if code in (429, 500, 503, 504):
                                key = f"inband_{code}"
                            else:
                                lats.append(
                                    (time.perf_counter() - t0) * 1e3
                                )
                        statuses[key] = statuses.get(key, 0) + 1

            # prime compile/caches with one wave (untimed)
            await asyncio.gather(*(one(b) for b in bodies[:concurrency]))
            decomp_box["before"] = _decomp_snapshot(server)
            for _wave in range(waves):
                lats.clear()
                statuses.clear()
                t0 = time.perf_counter()
                await asyncio.gather(*(one(b) for b in bodies))
                wall = time.perf_counter() - t0
                accepted = sorted(lats)
                wave_stats.append(
                    {
                        "wall": wall,
                        "rps": len(bodies) / wall,
                        "accepted": len(accepted),
                        "p50": pct(accepted, 0.5),
                        "p95": pct(accepted, 0.95),
                        "p99": pct(accepted, 0.99),
                        "statuses": dict(statuses),
                    }
                )

    try:
        asyncio.run(client())
        decomp = (
            _decompose(decomp_box["before"], _decomp_snapshot(server))
            if "before" in decomp_box else {}
        )
    finally:
        # the server must die even when a client assert trips — a live
        # second environment would skew every benchmark that follows
        loop_box["stop"] = True
        t.join(timeout=60)

    # a wave with ZERO accepted responses has p99 = pct([], .99) = 0.0 —
    # a fake best-case that would sort first and could become the median
    # exactly when shedding rejected everything; percentile aggregation
    # uses only waves that actually accepted traffic
    accepted_waves = [w for w in wave_stats if w["accepted"]]
    by_p99 = sorted(accepted_waves or wave_stats, key=lambda w: w["p99"])
    mid = by_p99[len(by_p99) // 2]
    total_statuses: dict[int, int] = {}
    for w in wave_stats:
        for code, c in w["statuses"].items():
            total_statuses[str(code)] = (
                total_statuses.get(str(code), 0) + c
            )
    batcher = server.batcher
    return {
        "p99": mid["p99"],
        "p99_min": by_p99[0]["p99"],
        "p99_max": by_p99[-1]["p99"],
        "p50": mid["p50"],
        "p95": mid["p95"],
        "rps": statistics.median(w["rps"] for w in wave_stats),
        "rps_min": min(w["rps"] for w in wave_stats),
        "rps_max": max(w["rps"] for w in wave_stats),
        "waves": len(wave_stats),
        "accepted_waves": len(accepted_waves),
        "n_requests": len(bodies),
        "statuses": total_statuses,
        "budget_routed_batches": batcher.budget_routed_batches,
        "host_fastpath_batches": batcher.host_fastpath_batches,
        "shed_requests": batcher.shed_requests,
        "expired_dropped": batcher.expired_dropped,
        "decomposition": decomp,
    }


def bench_http(
    n_requests: int = 2000,
    concurrency: int = 64,
    metric: str = "http_validate_latency_p99",
) -> None:
    s = _http_bench_core(n_requests, concurrency)
    p99 = s["p99"]
    emit(
        metric,
        p99,
        "ms",
        NORTH_STAR_P99_MS / p99 if p99 else 0.0,
        p50_ms=round(s["p50"], 2),
        p95_ms=round(s["p95"], 2),
        # spread across the timed waves (round-7 satellite: HTTP lines
        # now carry the same median/min/max the device lines do)
        p99_min_ms=round(s["p99_min"], 2),
        p99_max_ms=round(s["p99_max"], 2),
        waves=s["waves"],
        throughput_rps=round(s["rps"], 1),
        rps_min=round(s["rps_min"], 1),
        rps_max=round(s["rps_max"], 1),
        concurrency=concurrency,
        n_requests=s["n_requests"],
        budget_routed_batches=s["budget_routed_batches"],
        # this line's own host-side reference point: the measured
        # single-event-loop asyncio HTTP framing ceiling on this 1-core VM
        # (PROFILE.md) — the transport wall, independent of the device
        single_loop_ceiling_rps=1300,
        vs_single_loop_ceiling=round(s["rps"] / 1300.0, 4),
        # round-11 satellite: framing-vs-queue-vs-device attribution so
        # "batcher-bound" vs "framing-bound" is measurable per line
        decomposition=s["decomposition"],
        note="end-to-end HTTP through the micro-batcher on the real server",
    )


def bench_http_routing_ab(n_requests: int = 1500) -> None:
    """VERDICT Weak #3 closure: the latency-budget router's value (or
    no-op-ness) measured head to head at c64 — routing on vs off, with
    the host fast-path disabled so ONLY the budget router can route
    host-side, and budget_routed_batches reported so a no-op shows as
    exactly that."""
    on = _http_bench_core(
        n_requests, 64,
        {"host_fastpath_threshold": 0, "latency_budget_ms": 50.0},
    )
    off = _http_bench_core(
        n_requests, 64,
        {"host_fastpath_threshold": 0, "latency_budget_ms": 0.0},
    )
    p99 = on["p99"]
    emit(
        "http_validate_latency_routing_ab_c64",
        p99,
        "ms",
        NORTH_STAR_P99_MS / p99 if p99 else 0.0,
        routing_on_p99_ms=round(on["p99"], 2),
        routing_on_p99_min_ms=round(on["p99_min"], 2),
        routing_on_p99_max_ms=round(on["p99_max"], 2),
        routing_on_rps=round(on["rps"], 1),
        routing_on_budget_routed_batches=on["budget_routed_batches"],
        routing_off_p99_ms=round(off["p99"], 2),
        routing_off_p99_min_ms=round(off["p99_min"], 2),
        routing_off_p99_max_ms=round(off["p99_max"], 2),
        routing_off_rps=round(off["rps"], 1),
        waves=on["waves"],
        concurrency=64,
        note="host fast-path disabled on both sides; only the EWMA "
        "budget router differs — budget_routed_batches==0 means the "
        "router was a no-op at this load",
    )


def bench_http_overload_shedding(n_requests: int = 3000) -> None:
    """Round-7 acceptance: the c256-shaped overload run with load
    shedding ON (propagated request deadline + admission 429s) versus
    OFF. The claim under test: shedding bounds the p99 of ACCEPTED
    requests below the no-shedding p99, at a reported shed rate."""
    shed = _http_bench_core(
        n_requests, 256,
        {"request_timeout_ms": 400.0},
        allowed_statuses=(200, 429, 504),
    )
    raw = _http_bench_core(
        n_requests, 256,
        {"request_timeout_ms": 0.0},
    )
    p99 = shed["p99"]
    total = sum(shed["statuses"].values())
    # HTTP-level 429 = admission shed; in-band codes ride HTTP 200
    # (expired pre-encode drop = 504, bounded-wait overload = 429,
    # deadline-cut evaluation = 500) and are excluded from accepted-p99
    shed_count = shed["statuses"].get("429", 0) + shed["statuses"].get(
        "inband_429", 0
    )
    expired_count = shed["statuses"].get("inband_504", 0)
    emit(
        "http_overload_shedding_c256",
        p99,
        "ms (accepted p99, shedding on)",
        NORTH_STAR_P99_MS / p99 if p99 else 0.0,
        accepted_p99_shed_on_ms=round(shed["p99"], 2),
        accepted_p99_min_ms=round(shed["p99_min"], 2),
        accepted_p99_max_ms=round(shed["p99_max"], 2),
        p99_shed_off_ms=round(raw["p99"], 2),
        p99_shed_off_min_ms=round(raw["p99_min"], 2),
        p99_shed_off_max_ms=round(raw["p99_max"], 2),
        shed_rate=round(shed_count / max(1, total), 4),
        shed_429s=shed_count,
        expired_inband_504s=expired_count,
        deadline_inband_500s=shed["statuses"].get("inband_500", 0),
        accepted_200s=shed["statuses"].get("200", 0),
        batcher_shed_requests=shed["shed_requests"],
        batcher_expired_dropped=shed["expired_dropped"],
        rps_shed_on=round(shed["rps"], 1),
        rps_shed_off=round(raw["rps"], 1),
        waves=shed["waves"],
        accepted_waves=shed["accepted_waves"],
        concurrency=256,
        request_timeout_ms=400.0,
        note="request deadline 400ms: admission sheds what the queue "
        "cannot serve in time (429 + Retry-After), expired queued rows "
        "drop pre-encode (504); accepted-request p99 vs the unshed run",
    )


# ---------------------------------------------------------------------------
# Native HTTP front-end (round-11 acceptance)
# ---------------------------------------------------------------------------


def _native_client_main(argv: list[str]) -> int:
    """Raw-socket load-generator subprocess for the native-frontend bench:
    keep-alive connections with pipelining (depth requests outstanding per
    connection), per-RESPONSE latencies measured from the pipelined
    batch's send. A separate process because an in-process asyncio client
    caps at the very Python framing ceiling this bench exists to beat."""
    import socket
    import threading

    port, corpus_path, conns, per, depth = (
        int(argv[0]), argv[1], int(argv[2]), int(argv[3]), int(argv[4])
    )
    reqs: list[bytes] = []
    blob = open(corpus_path, "rb").read()
    off = 0
    while off < len(blob):
        n = int.from_bytes(blob[off : off + 4], "little")
        off += 4
        reqs.append(blob[off : off + n])
        off += n
    lats: list[float] = []
    statuses: dict[str, int] = {}
    lock = threading.Lock()

    def one_conn(widx: int) -> None:
        s = socket.create_connection(("127.0.0.1", port))
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        buf = b""
        my: list[tuple[float, int]] = []
        n = len(reqs)
        for i in range(per):
            base = (widx * per + i) * depth
            batch = b"".join(reqs[(base + k) % n] for k in range(depth))
            t0 = time.perf_counter()
            s.sendall(batch)
            got = 0
            while got < depth:
                he = buf.find(b"\r\n\r\n")
                if he >= 0:
                    cl = 0
                    for ln in buf[:he].split(b"\r\n")[1:]:
                        if ln[:15].lower() == b"content-length:":
                            cl = int(ln[15:])
                            break
                    total = he + 4 + cl
                    if len(buf) >= total:
                        code = int(buf[9:12])
                        buf = buf[total:]
                        got += 1
                        my.append(((time.perf_counter() - t0) * 1e3, code))
                        continue
                chunk = s.recv(262144)
                if not chunk:
                    raise ConnectionError("server closed mid-wave")
                buf += chunk
        s.close()
        with lock:
            for lat, code in my:
                lats.append(lat)
                statuses[str(code)] = statuses.get(str(code), 0) + 1

    threads = [
        threading.Thread(target=one_conn, args=(w,)) for w in range(conns)
    ]
    t0 = time.perf_counter()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    wall = time.perf_counter() - t0
    lats.sort()
    print(
        json.dumps(
            {
                "n": len(lats),
                "wall": wall,
                "rps": len(lats) / wall,
                "p50": pct(lats, 0.5),
                "p95": pct(lats, 0.95),
                "p99": pct(lats, 0.99),
                "max": lats[-1] if lats else 0.0,
                "statuses": statuses,
            }
        ),
        flush=True,
    )
    return 0


def _native_bench_core(
    conns: int,
    depth: int,
    per_conn: int,
    config_overrides: dict | None = None,
    waves: int = 3,
    n_corpus: int = 4000,
) -> dict:
    """Boot a REAL server and drive it with the raw-socket pipelined
    client subprocess (conns × depth outstanding requests). Returns
    per-wave stats + the framing/queue/device decomposition."""
    import asyncio
    import tempfile
    import threading

    from policy_server_tpu.config.config import Config
    from policy_server_tpu.policies.flagship import (
        flagship_policies,
        synthetic_firehose,
    )
    from policy_server_tpu.server import PolicyServer

    cfg = dict(
        addr="127.0.0.1",
        port=0,
        readiness_probe_port=0,
        policies=flagship_policies(),
        max_batch_size=256,
        batch_timeout_ms=1.0,
        policy_timeout_seconds=30.0,
    )
    cfg.update(config_overrides or {})
    server = PolicyServer.new_from_config(Config(**cfg))

    loop_box: dict = {}
    started = threading.Event()

    def run_server() -> None:
        loop = asyncio.new_event_loop()
        loop_box["loop"] = loop
        asyncio.set_event_loop(loop)

        async def main() -> None:
            await server.start()
            started.set()
            while not loop_box.get("stop"):
                await asyncio.sleep(0.05)
            await server.stop()

        loop.run_until_complete(main())

    t = threading.Thread(target=run_server, daemon=True)
    t.start()
    if not started.wait(timeout=600):
        raise RuntimeError("bench server failed to start")
    port = server.api_port
    native = getattr(server, "_native_frontend", None) is not None

    docs = synthetic_firehose(n_corpus, seed=77)
    corpus = tempfile.NamedTemporaryFile(
        prefix="bench-native-corpus-", suffix=".bin", delete=False
    )
    for d in docs:
        body = json.dumps(
            {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
             "request": d["request"]}
        ).encode()
        req = (
            b"POST /validate/pod-security-group HTTP/1.1\r\nHost: b\r\n"
            b"Content-Type: application/json\r\nContent-Length: "
            + str(len(body)).encode() + b"\r\n\r\n" + body
        )
        corpus.write(len(req).to_bytes(4, "little") + req)
    corpus.close()

    def client_wave(wave_conns, wave_per, wave_depth) -> dict:
        out = subprocess.run(
            [
                sys.executable, os.path.abspath(__file__), "--native-client",
                str(port), corpus.name, str(wave_conns), str(wave_per),
                str(wave_depth),
            ],
            capture_output=True, text=True, timeout=900, check=True,
        )
        return json.loads(out.stdout.strip().splitlines()[-1])

    try:
        client_wave(max(2, conns // 4), 4, depth)  # prime compile/caches
        before = _decomp_snapshot(server)
        wave_stats = [client_wave(conns, per_conn, depth) for _ in range(waves)]
        decomp = _decompose(before, _decomp_snapshot(server))
        nf = getattr(server, "_native_frontend", None)
        nstats = nf.stats() if nf is not None else {}
        bstats = server.batcher.stats_snapshot()
    finally:
        loop_box["stop"] = True
        t.join(timeout=60)
        os.unlink(corpus.name)

    by_p99 = sorted(wave_stats, key=lambda w: w["p99"])
    mid = by_p99[len(by_p99) // 2]
    statuses: dict[str, int] = {}
    for w in wave_stats:
        for k, v in w["statuses"].items():
            statuses[k] = statuses.get(k, 0) + v
    return {
        "native": native,
        "p99": mid["p99"],
        "p99_min": by_p99[0]["p99"],
        "p99_max": by_p99[-1]["p99"],
        "p50": mid["p50"],
        "p95": mid["p95"],
        "rps": statistics.median(w["rps"] for w in wave_stats),
        "rps_min": min(w["rps"] for w in wave_stats),
        "rps_max": max(w["rps"] for w in wave_stats),
        "waves": len(wave_stats),
        "n_requests": sum(w["n"] for w in wave_stats),
        "statuses": statuses,
        "decomposition": decomp,
        "native_stats": nstats,
        "avg_batch": round(
            bstats["requests_dispatched"]
            / max(1, bstats["batches_dispatched"]), 1,
        ),
    }


def bench_http_native(quick: bool = False) -> None:
    """Round-11 acceptance line: end-to-end HTTP through the NATIVE
    (GIL-free C++) frontend at 256 outstanding requests, shedding off,
    throughput-oriented batcher knobs (fastpath off — everything rides
    the batched device/dedup path), against the SAME raw-socket client
    driving the Python frontend for the A/B. The decomposition makes the
    bound attributable: framing_ms_per_req is the native framing share,
    queue+encode+device the batcher share."""
    overrides = {
        "request_timeout_ms": 0.0,  # shedding OFF per the acceptance line
        "host_fastpath_threshold": 0,
        "latency_budget_ms": 0.0,
        "max_batch_size": 512,
        "batch_timeout_ms": 8.0,
    }
    per = 12 if quick else 40
    nat = _native_bench_core(
        16, 16, per, {**overrides, "frontend": "native"},
    )
    if not nat["native"]:
        # the extension failed to build/load and the server fell back to
        # aiohttp: recording those numbers under the native key would
        # falsify the acceptance artifact
        emit(
            "http_validate_native", 0.0, "error", 0.0,
            error="native frontend unavailable (httpfront.cpp failed to "
            "build/load); server fell back to the Python frontend — "
            "no native number to record",
        )
        return
    py = _native_bench_core(
        16, 16, max(4, per // 4), {**overrides, "frontend": "python"},
    )
    p99 = nat["p99"]
    framing_share = nat["decomposition"].get("framing_ms_per_req", 0.0)
    emit(
        "http_validate_native",
        nat["rps"],
        "req/s (c256, shedding off)",
        nat["rps"] / 20000.0,  # the round-11 acceptance floor
        p50_ms=round(nat["p50"], 2),
        p95_ms=round(nat["p95"], 2),
        p99_ms=round(p99, 2),
        p99_min_ms=round(nat["p99_min"], 2),
        p99_max_ms=round(nat["p99_max"], 2),
        rps_min=round(nat["rps_min"], 1),
        rps_max=round(nat["rps_max"], 1),
        waves=nat["waves"],
        n_requests=nat["n_requests"],
        statuses=nat["statuses"],
        avg_batch=nat["avg_batch"],
        decomposition=nat["decomposition"],
        native_framing_us_per_req=round(
            nat["native_stats"].get("framing_ns", 0)
            / 1e3 / max(1, nat["native_stats"].get("http_requests", 1)), 1,
        ),
        python_frontend_rps=round(py["rps"], 1),
        python_frontend_p99_ms=round(py["p99"], 2),
        python_frontend_decomposition=py["decomposition"],
        speedup_vs_python_frontend=round(nat["rps"] / max(1.0, py["rps"]), 2),
        client="raw-socket subprocess, 16 conns x 16 pipelined (c256); "
        "client and server share the 2-core dev box",
        note="native frontend: the per-request framing share is "
        f"{framing_share:.3f} ms — the serving stack is batcher-bound "
        "now (queue+encode+device dominate); vs_baseline is against the "
        "20k rps/process acceptance floor, which this 2-core dev box "
        "cannot reach end-to-end because the BATCHER serving path alone "
        "caps near 6.5k req/s here (the framing layer itself sustains "
        ">20k req/s against an immediate-completion sink)",
    )


# ---------------------------------------------------------------------------
# Mixed live + audit (round-10 acceptance)
# ---------------------------------------------------------------------------


def bench_audit_mixed(
    n_resources: int = 2000, duration_s: float = 4.0
) -> None:
    """Round-10 acceptance line: a sustained live stream at ~70% of the
    measured batcher capacity, first with the background audit scanner
    OFF (baseline live p99), then with it sweeping a 2k-resource
    snapshot continuously on the best-effort lane. Reports audit rows/s
    harvested from idle slots and the live p99 delta — the claim under
    test: live p99 within 10% of the audit-off baseline while audit
    harvests >=1k rows/s of idle capacity."""
    import threading
    from types import SimpleNamespace

    from policy_server_tpu.api.service import RequestOrigin
    from policy_server_tpu.audit import (
        AuditScanner,
        PolicyReportStore,
        SnapshotStore,
    )
    from policy_server_tpu.runtime.batcher import MicroBatcher

    env = build_env(
        {
            "pod-privileged": {"module": "builtin://pod-privileged"},
            "namespace-validate": {
                "module": "builtin://namespace-validate",
                "settings": {"denied_namespaces": ["kube-system"]},
            },
        }
    )
    batcher = MicroBatcher(
        env,
        max_batch_size=128,
        batch_timeout_ms=1.0,
        policy_timeout=30.0,
        # the DEFAULT serving shape: small live batches answer on the
        # host fast-path / budget router while audit occupies the device
        # — the designed division of labor the preemption contract plus
        # routing protect
        host_fastpath_threshold=64,
        latency_budget_ms=50.0,
    ).start()
    try:
        batcher.warmup()
        corpus = build_requests(n_resources + 2000, seed=7)
        snapshot = SnapshotStore(max_bytes=256 * 1024 * 1024)
        snapshot.observe(corpus[:n_resources])
        live_reqs = corpus[n_resources:]

        # capacity: blast one batch-saturating burst, unpaced
        burst = live_reqs[:1024]
        t0 = time.perf_counter()
        futs = [
            batcher.submit("pod-privileged", r, RequestOrigin.VALIDATE)
            for r in burst
        ]
        for f in futs:
            f.result(timeout=120)
        capacity_rps = len(burst) / (time.perf_counter() - t0)
        target_rps = 0.7 * capacity_rps

        def drive_live(duration: float) -> list[float]:
            """Paced live stream at target_rps; per-request latency via
            completion callbacks (groups of 16, real idle gaps between
            groups — the slots the audit lane may claim)."""
            lats: list[float] = []
            lock = threading.Lock()
            group = 16
            interval = group / target_rps
            submitted = 0
            next_t = time.perf_counter()
            t_end = next_t + duration
            i = 0
            while time.perf_counter() < t_end:
                for _ in range(group):
                    r = live_reqs[i % len(live_reqs)]
                    i += 1
                    t1 = time.perf_counter()
                    f = batcher.submit(
                        "pod-privileged", r, RequestOrigin.VALIDATE
                    )

                    def done(fut, t1=t1):
                        dt = (time.perf_counter() - t1) * 1e3
                        with lock:
                            lats.append(dt)

                    f.add_done_callback(done)
                    submitted += 1
                next_t += interval
                time.sleep(max(0.0, next_t - time.perf_counter()))
            deadline = time.perf_counter() + 60
            while time.perf_counter() < deadline:
                with lock:
                    if len(lats) >= submitted:
                        break
                time.sleep(0.01)
            with lock:
                return sorted(lats)

        # baseline: audit off
        off = drive_live(duration_s)

        # audit on: a continuous full-sweep loop (the saturating shape —
        # a real deployment sweeps on promote/interval, this measures
        # the harvest ceiling)
        state = SimpleNamespace(
            evaluation_environment=env, batcher=batcher, lifecycle=None
        )
        scanner = AuditScanner(
            state=state,
            snapshot=snapshot,
            reports=PolicyReportStore(),
            mode="interval",
            interval_seconds=3600.0,
            batch_size=128,
        )
        sweep_stop = threading.Event()

        def sweeper() -> None:
            while not sweep_stop.is_set():
                try:
                    scanner.sweep(full=True)
                except Exception:  # noqa: BLE001 — bench best-effort
                    return

        sweeper_thread = threading.Thread(target=sweeper, daemon=True)
        rows_before = scanner.stats()["rows_scanned"]
        t_on = time.perf_counter()
        sweeper_thread.start()
        on = drive_live(duration_s)
        on_wall = time.perf_counter() - t_on
        sweep_stop.set()
        rows_after = scanner.stats()["rows_scanned"]
        audit_rows_per_s = (rows_after - rows_before) / on_wall

        p99_off = pct(off, 0.99)
        p99_on = pct(on, 0.99)
        snap = batcher.stats_snapshot()
        emit(
            "mixed_live_audit_scan",
            audit_rows_per_s,
            "audit rows/s",
            audit_rows_per_s / 1000.0,  # acceptance: >=1k rows/s harvest
            live_target_rps=round(target_rps, 1),
            live_capacity_rps=round(capacity_rps, 1),
            live_p99_audit_off_ms=round(p99_off, 2),
            live_p99_audit_on_ms=round(p99_on, 2),
            live_p50_audit_off_ms=round(pct(off, 0.5), 2),
            live_p50_audit_on_ms=round(pct(on, 0.5), 2),
            p99_delta_pct=round(
                100.0 * (p99_on - p99_off) / p99_off, 1
            ) if p99_off else 0.0,
            audit_resources=n_resources,
            audit_policies=2,
            audit_batches_dispatched=snap["audit_batches_dispatched"],
            audit_preemptions=snap["audit_preemptions"],
            live_requests_off=len(off),
            live_requests_on=len(on),
            duration_s=duration_s,
            note="sustained live at ~70% capacity; scanner sweeping a "
            "2k-resource snapshot continuously on the best-effort lane "
            "(idle-only dispatch, single in-flight audit batch)",
        )
    finally:
        batcher.shutdown()
        env.close()


# ---------------------------------------------------------------------------
# Wasm escape-hatch path: interpreter reviews/s (VERDICT r3 weak #4)
# ---------------------------------------------------------------------------


def bench_wasm(requests) -> None:
    """Cost of the host wasm engine — the generality escape hatch for
    policies outside the predicate IR. Measures reviews/s through the waPC
    WAT oracle policy and (when the upstream fixture is present) an
    upstream-compiled Gatekeeper module, on whichever engine the ABI
    hosts select (the native C++ core when it builds, else the Python
    reference interpreter). Its own baseline: the reference runs these
    under wasmtime's cranelift-JIT at ≈1 ms/request (≈1k reviews/s
    end-to-end, dominated by non-wasm overhead)."""
    import pathlib

    from policy_server_tpu.policies.wasm_oracle import oracle_policy
    from policy_server_tpu.wasm.opa import OpaPolicy, gatekeeper_validate

    ref_single_rps = 1_000.0
    docs = [r.payload() for r in requests[:200]]

    pol = oracle_policy("pod-privileged")
    pol.validate(docs[0], {})  # prime (assemble + decode)
    t0 = time.perf_counter()
    for d in docs:
        pol.validate(d, {})
    wapc_wall = time.perf_counter() - t0
    wapc_rps = len(docs) / wapc_wall

    gk_rps = None
    gk_note = None
    fixture = pathlib.Path(
        os.environ.get("REFERENCE_DIR", "/root/reference"),
        "tests/data/gatekeeper_always_happy_policy.wasm",
    )
    if fixture.exists():
        opa = OpaPolicy(fixture.read_bytes())
        gk_docs = docs[:20]  # upstream module: heavier per call
        gatekeeper_validate(opa, gk_docs[0], parameters={})
        t0 = time.perf_counter()
        for d in gk_docs:
            gatekeeper_validate(opa, d, parameters={})
        gk_rps = len(gk_docs) / (time.perf_counter() - t0)
    else:
        gk_note = f"skipped: fixture not found at {fixture} (set REFERENCE_DIR)"

    emit(
        "wasm_interpreter_reviews_per_sec",
        wapc_rps,
        "reviews/s",
        wapc_rps / ref_single_rps,
        wat_wapc_rps=round(wapc_rps, 1),
        gatekeeper_fixture_rps=round(gk_rps, 1) if gk_rps else gk_note,
        n_requests=len(docs),
        baseline="reference wasmtime-JIT sync path ≈1k reviews/s; the "
        "wasm engine is the correctness escape hatch, not the serving path",
        native_engine=__import__(
            "policy_server_tpu.wasm.native_exec", fromlist=["available"]
        ).available(),
    )


# ---------------------------------------------------------------------------
# Config 4 (headline): 32-policy firehose
# ---------------------------------------------------------------------------


def build_rollout_stream(n_requests: int, replicas: int, seed: int):
    """The realistic admission firehose: ``n/replicas`` unique pod
    templates, each admitted ``replicas`` times in a burst — a Deployment
    rollout admits its replica pods back-to-back, identical except for
    the generated pod name and the API server's fresh uid. Returns
    (stream_requests, unique_requests)."""
    import copy

    from policy_server_tpu.models import (
        AdmissionReviewRequest,
        ValidateRequest,
    )
    from policy_server_tpu.policies.flagship import synthetic_firehose

    n_unique = max(1, n_requests // replicas)
    uniq_docs = synthetic_firehose(n_unique, seed=seed)
    stream_docs = []
    for d in uniq_docs:
        for r in range(replicas):
            dd = copy.deepcopy(d)
            dd["request"]["uid"] = f'{dd["request"]["uid"]}-r{r}'
            obj = dd["request"].get("object") or {}
            meta = obj.setdefault("metadata", {})
            meta["name"] = f'{meta.get("name", "pod")}-{r}'
            dd["request"]["name"] = meta["name"]
            stream_docs.append(dd)

    def to_req(doc):
        return ValidateRequest.from_admission(
            AdmissionReviewRequest.from_dict(doc).request
        )

    return [to_req(d) for d in stream_docs], [to_req(d) for d in uniq_docs]


def profile_delta(after: dict, before: dict) -> dict:
    """Per-row host decomposition between two host_profile snapshots:
    encode / dedup-bookkeeping / dispatch-wait in µs/row (PROFILE.md r6).
    Every number here is recoverable from the emitted BENCH JSON alone."""
    d = {k: after.get(k, 0) - before.get(k, 0) for k in after}
    enc_rows = max(1, d.get("encode_rows", 0))
    book_rows = max(1, d.get("bookkeeping_rows", 0))
    disp_rows = max(1, d.get("dispatched_rows", 0))
    return {
        "encode_us_per_row": round(d.get("encode_ns", 0) / 1e3 / enc_rows, 2),
        "encode_rows": d.get("encode_rows", 0),
        "bookkeeping_us_per_row": round(
            d.get("bookkeeping_ns", 0) / 1e3 / book_rows, 2
        ),
        "bookkeeping_rows": d.get("bookkeeping_rows", 0),
        "dispatch_wait_us_per_dispatched_row": round(
            d.get("dispatch_wait_ns", 0) / 1e3 / disp_rows, 2
        ),
        "dispatched_rows": d.get("dispatched_rows", 0),
        "dispatched_chunks": d.get("dispatched_chunks", 0),
    }


def bench_config4(n_requests: int, batch_size: int) -> None:
    from policy_server_tpu.policies.flagship import flagship_policies

    from policy_server_tpu.evaluation.environment import (
        EvaluationEnvironmentBuilder,
    )

    REPLICAS = 8
    stream, uniq = build_rollout_stream(n_requests, REPLICAS, seed=42)
    n_requests = len(stream)
    policy_id = "pod-security-group"  # every dispatch computes ALL verdicts
    items = [(policy_id, r) for r in stream]
    uniq_items = [(policy_id, r) for r in uniq]

    env = EvaluationEnvironmentBuilder(backend="jax").build(flagship_policies())

    # dispatch-size sweep: on a remote/tunneled device the per-chunk fetch
    # round-trip dominates, so bigger chunks amortize it — measure instead
    # of assuming (compiles happen here, outside the timed run). Transport
    # throughput drifts run to run (measured ±40% across consecutive
    # identical runs), so probe every size in TWO interleaved rounds and
    # keep each size's best — a single ordered pass would systematically
    # favor whichever size ran last (warmest).
    candidates = [
        bs for bs in sorted({batch_size, 2048, 4096})
        if bs <= max(64, len(items))
    ]
    sweep: dict[int, float] = {}
    for bs in candidates:
        env.max_dispatch_batch = bs
        env.warmup((bs,))
        env.reset_verdict_cache()
        env.validate_batch(items[: min(2 * bs, len(items))])  # prime size
    for _round in range(2):
        for bs in candidates:
            env.max_dispatch_batch = bs
            env.reset_verdict_cache()
            probe = items[: min(2 * bs, len(items))]
            t0 = time.perf_counter()
            env.validate_batch(probe)
            rps = len(probe) / (time.perf_counter() - t0)
            sweep[bs] = max(sweep.get(bs, 0.0), rps)
    if sweep:  # tiny n_requests may skip every candidate
        batch_size = max(sweep, key=sweep.get)
    env.max_dispatch_batch = batch_size

    # prime with a FULL pass from an empty cache: the timed passes then
    # replay the exact same chunk/compaction shapes (every bucket already
    # compiled), per the r3/r4 lesson that priming at a different shape
    # puts XLA compilation inside the timed region
    env.reset_verdict_cache()
    env.validate_batch(items)
    fallbacks_before = env.oracle_fallbacks  # report the timed-pass DELTA
    dedup_before = dict(env.dedup_stats)
    profile_before = env.host_profile
    rps_runs = []
    for _ in range(3):
        env.reset_verdict_cache()  # each pass does the same work
        t_start = time.perf_counter()
        results = env.validate_batch(items)
        rps_runs.append(len(items) / (time.perf_counter() - t_start))
        errors = [r for r in results if isinstance(r, Exception)]
        if errors:
            raise RuntimeError(f"bench evaluation error: {errors[0]}")
    s_on = spread(rps_runs)
    dedup_after = env.dedup_stats
    rollout_profile = profile_delta(env.host_profile, profile_before)
    dedup_total = (
        dedup_after["cache_hits"] - dedup_before["cache_hits"]
        + dedup_after["blob_cache_hits"] - dedup_before["blob_cache_hits"]
        + dedup_after["batch_dup_hits"] - dedup_before["batch_dup_hits"]
    )
    dedup_rate = dedup_total / max(1, 3 * len(items))
    dedup_tiers = {
        "blob_tier_hits": dedup_after["blob_cache_hits"]
        - dedup_before["blob_cache_hits"],
        "row_tier_hits": dedup_after["cache_hits"]
        - dedup_before["cache_hits"],
        "in_batch_dup_hits": dedup_after["batch_dup_hits"]
        - dedup_before["batch_dup_hits"],
        "cache_bytes": dedup_after["cache_bytes"]
        + dedup_after["blob_cache_bytes"],
    }

    fallbacks_on = env.oracle_fallbacks - fallbacks_before

    # the honest no-dedup numbers on the SAME stream (cache-off build) +
    # the all-unique-rows workload (cross-round comparable with r1-r4)
    env.close()
    env_off = EvaluationEnvironmentBuilder(
        backend="jax", verdict_cache_size=0
    ).build(flagship_policies())
    env_off.max_dispatch_batch = batch_size
    env_off.warmup((batch_size,))
    env_off.validate_batch(items)  # full prime
    off_runs = []
    for _ in range(3):
        t0 = time.perf_counter()
        env_off.validate_batch(items)
        off_runs.append(len(items) / (time.perf_counter() - t0))
    s_off = spread(off_runs)
    env_off.validate_batch(uniq_items)  # prime the unique-only shapes
    uniq_profile_before = env_off.host_profile
    uniq_runs = []
    for _ in range(3):
        t0 = time.perf_counter()
        env_off.validate_batch(uniq_items)
        uniq_runs.append(len(uniq_items) / (time.perf_counter() - t0))
    s_uniq = spread(uniq_runs)
    uniq_profile = profile_delta(env_off.host_profile, uniq_profile_before)

    # steady-state per-dispatch latency at a serving-sized batch, on the
    # CACHE-OFF environment: this metric means "one device round-trip at
    # batch N" — a cache would answer host-side and measure nothing
    lat_batch = min(256, batch_size)
    lat_items = uniq_items[:lat_batch]
    env_off.validate_batch(lat_items)
    lats = []
    for _ in range(100):
        t0 = time.perf_counter()
        env_off.validate_batch(lat_items)
        lats.append((time.perf_counter() - t0) * 1e3)
    lats.sort()
    env_off.close()

    # The dedup-on rollout number moved OFF the historical key in round 6
    # (ADVICE r5 #5): ``admission_reviews_per_sec_32policies`` measured an
    # all-unique no-dedup stream in rounds 1-4, so the historical key
    # carries that workload again (emitted last, below) and the rollout
    # stream gets its own metric here.
    emit(
        "admission_reviews_per_sec_32policies_rollout_dedup",
        s_on["median"],
        "reviews/s/chip",
        s_on["median"] / NORTH_STAR_RPS,
        n_requests=n_requests,
        batch_size=batch_size,
        workload=(
            f"rollout firehose: {len(uniq_items)} unique pod templates x "
            f"{REPLICAS} replica admissions each (bursty, fresh uid+name "
            f"per replica) — two-tier dedup: blob tier collapses exact "
            f"replays pre-encode, row tier collapses uid/name variants "
            f"post-encode"
        ),
        rps_min=round(s_on["min"], 1),
        rps_max=round(s_on["max"], 1),
        rps_runs=s_on["runs"],
        dedup_rate=round(dedup_rate, 4),
        dedup_tiers=dedup_tiers,
        host_decomposition_us_per_row=rollout_profile,
        unique_templates=len(uniq_items),
        replicas=REPLICAS,
        rps_no_dedup_same_stream=round(s_off["median"], 1),
        rps_no_dedup_min=round(s_off["min"], 1),
        rps_no_dedup_max=round(s_off["max"], 1),
        n_policies=32,
        oracle_fallbacks=fallbacks_on,
    )

    # HEADLINE (the driver records the LAST line): all-unique stream, no
    # dedup — the exact workload rounds 1-4 published under this key, so
    # cross-round trend lines stay apples-to-apples (ADVICE r5 #5).
    emit(
        "admission_reviews_per_sec_32policies",
        s_uniq["median"],
        "reviews/s/chip",
        s_uniq["median"] / NORTH_STAR_RPS,
        n_requests=len(uniq_items),
        batch_size=batch_size,
        workload=(
            "all-unique synthetic firehose, verdict cache OFF — the "
            "historical config4 workload (rounds 1-4); the rollout-dedup "
            "figure lives in admission_reviews_per_sec_32policies_rollout_dedup"
        ),
        rps_min=round(s_uniq["min"], 1),
        rps_max=round(s_uniq["max"], 1),
        rps_runs=s_uniq["runs"],
        host_decomposition_us_per_row=uniq_profile,
        rps_rollout_dedup=round(s_on["median"], 1),
        rps_rollout_dedup_min=round(s_on["min"], 1),
        rps_rollout_dedup_max=round(s_on["max"], 1),
        rps_no_dedup_same_rollout_stream=round(s_off["median"], 1),
        p50_dispatch_latency_ms=round(pct(lats, 0.5), 2),
        p95_dispatch_latency_ms=round(pct(lats, 0.95), 2),
        p99_dispatch_latency_ms=round(pct(lats, 0.99), 2),
        dispatch_latency_samples=len(lats),
        latency_dispatch_size=lat_batch,
        n_policies=32,
        oracle_fallbacks=fallbacks_on,
        dispatch_size_sweep={str(k): round(v, 1) for k, v in sweep.items()},
    )


def main() -> int:
    if "--config5-child" in sys.argv:
        bench_config5_child()
        return 0
    if "--native-client" in sys.argv:
        i = sys.argv.index("--native-client")
        return _native_client_main(sys.argv[i + 1 : i + 6])
    n_requests = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    batch_size = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
    quick = os.environ.get("BENCH_QUICK") == "1"
    if quick:
        n_requests = min(n_requests, 8192)

    requests = build_requests(max(4096, min(n_requests, 8192)), seed=42)
    # error lines reuse the SUCCESS metric names so consumers keyed on the
    # documented names see value 0 + error, not a vanished line
    config_metrics = {
        bench_config1: "config1_namespace_validate_single",
        bench_config2: "config2_psp_pair_1k_replay",
        bench_config3: "config3_image_signatures_group",
        bench_wasm: "wasm_interpreter_reviews_per_sec",
    }
    for fn, metric in config_metrics.items():
        try:
            fn(requests)
        except Exception as e:  # noqa: BLE001 — one config must not kill the run
            emit(metric, 0.0, "error", 0.0, error=repr(e)[:300])
    try:
        bench_config5()
    except Exception as e:  # noqa: BLE001
        emit("config5_multitenant_8shards_virtual", 0.0, "error", 0.0,
             error=repr(e)[:300])
    try:
        # moderate concurrency: batches stay under the host-fastpath
        # threshold, so this measures the LATENCY serving path
        bench_http(
            n_requests=512 if quick else 2000,
            concurrency=64,
            metric="http_validate_latency_p99_c64",
        )
    except Exception as e:  # noqa: BLE001
        emit("http_validate_latency_p99_c64", 0.0, "error", 0.0,
             error=repr(e)[:300])
    try:
        # concurrency 256 ≈ the knee of this transport's throughput curve
        # (890 rps @ p99 492 ms after the async-logging/metrics-cache
        # work; 1024 concurrent only adds queue wait — the Python asyncio
        # HTTP framing caps ~1.3k rps/loop, PROFILE.md)
        bench_http(
            n_requests=512 if quick else 4000,
            concurrency=64 if quick else 256,
        )
    except Exception as e:  # noqa: BLE001
        emit("http_validate_latency_p99", 0.0, "error", 0.0,
             error=repr(e)[:300])
    try:
        # native (GIL-free C++) frontend at c256, shedding off, vs the
        # Python frontend under the same raw-socket client (round-11)
        bench_http_native(quick=quick)
    except Exception as e:  # noqa: BLE001
        emit("http_validate_native", 0.0, "error", 0.0, error=repr(e)[:300])
    try:
        # latency-budget router A/B at c64 (VERDICT Weak #3 closure)
        bench_http_routing_ab(n_requests=512 if quick else 1500)
    except Exception as e:  # noqa: BLE001
        emit("http_validate_latency_routing_ab_c64", 0.0, "error", 0.0,
             error=repr(e)[:300])
    try:
        # c256 overload with load shedding on vs off (round-7 acceptance)
        bench_http_overload_shedding(n_requests=512 if quick else 3000)
    except Exception as e:  # noqa: BLE001
        emit("http_overload_shedding_c256", 0.0, "error", 0.0,
             error=repr(e)[:300])
    try:
        # mixed live+audit: scanner harvest on idle slots vs live p99
        # (round-10 acceptance)
        bench_audit_mixed(
            n_resources=512 if quick else 2000,
            duration_s=2.0 if quick else 4.0,
        )
    except Exception as e:  # noqa: BLE001
        emit("mixed_live_audit_scan", 0.0, "error", 0.0,
             error=repr(e)[:300])
    # compact recap of every line so far: the driver's tail window
    # truncated BENCH_r04 and lost config1-3 — this single line preserves
    # every number even if only the last two lines survive
    print(
        json.dumps(
            {
                "metric": "bench_summary",
                "value": len(_EMITTED),
                "unit": "lines",
                "vs_baseline": 0,
                "details": {m: [v, u] for m, v, u in _EMITTED},
            }
        ),
        flush=True,
    )
    # headline LAST: the driver records the final JSON line
    try:
        bench_config4(n_requests, batch_size)
    except Exception as e:  # noqa: BLE001 — the headline line must exist
        emit("admission_reviews_per_sec_32policies", 0.0, "error", 0.0,
             error=repr(e)[:300])
    return 0


if __name__ == "__main__":
    sys.exit(main())
