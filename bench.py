"""Benchmark suite shim — the suite itself lives in ``tools/bench/``
(round 12: the single file outgrew its shape, ROADMAP item 5). This
entrypoint, its arguments, and every emitted BENCH json key are
unchanged:

    python bench.py [n_requests] [batch_size]

One JSON line per benchmark, the HEADLINE line LAST (config 4, the
32-policy firehose — the driver's recorded metric). Subprocess entry
points (``--config5-child``, ``--native-client``) also route through
here so child invocations stay `python bench.py ...`."""

from __future__ import annotations

import sys
from pathlib import Path

# invoked as a script: the repo root must be importable for tools.bench
_ROOT = str(Path(__file__).resolve().parent)
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from tools.bench.main import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
