"""Benchmark: the flagship config — 32 mixed policies, synthetic
AdmissionReview firehose (BASELINE.md config 4).

Measures the full evaluation pipeline per review (encode → batched fused
device dispatch → response materialization, i.e. everything the server does
minus HTTP framing) and prints ONE JSON line:

    {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

``vs_baseline`` is value / 100_000 — the north-star target from
BASELINE.json (the reference publishes no benchmark numbers; ≥1.0 means the
target is met on this hardware).
"""

from __future__ import annotations

import json
import sys
import time


def main() -> int:
    n_requests = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    batch_size = int(sys.argv[2]) if len(sys.argv) > 2 else 1024

    from policy_server_tpu.evaluation.environment import (
        EvaluationEnvironmentBuilder,
    )
    from policy_server_tpu.models import AdmissionReviewRequest, ValidateRequest
    from policy_server_tpu.policies.flagship import (
        flagship_policies,
        synthetic_firehose,
    )

    env = EvaluationEnvironmentBuilder(backend="jax").build(flagship_policies())

    # Pre-parse the firehose into requests (JSON/HTTP framing is out of
    # scope for this metric; a distinct corpus per request keeps the
    # encode path honest).
    docs = synthetic_firehose(n_requests, seed=42)
    requests = [
        ValidateRequest.from_admission(
            AdmissionReviewRequest.from_dict(doc).request
        )
        for doc in docs
    ]
    policy_id = "pod-security-group"  # the batcher computes ALL verdicts per
    # dispatch; target choice only affects materialization.

    # Warmup: compile the fused program for the bench bucket.
    env.max_dispatch_batch = batch_size
    env.warmup((batch_size,))

    # Throughput: the full firehose through ONE validate_batch call — the
    # environment chunks to `batch_size` dispatches internally, encodes on
    # a GIL-free thread pool, and drains results on a fetch pool (see
    # PROFILE.md for the transport profile this shape optimizes). A short
    # priming pass first: the remote relay's first chunks include
    # warm-path artifacts that are not steady-state.
    env.validate_batch([(policy_id, r) for r in requests[:batch_size]])
    t_start = time.perf_counter()
    results = env.validate_batch([(policy_id, r) for r in requests])
    wall = time.perf_counter() - t_start
    errors = [r for r in results if isinstance(r, Exception)]
    if errors:
        raise RuntimeError(f"bench evaluation error: {errors[0]}")

    # Serving latency: steady-state per-dispatch latency at a serving-sized
    # batch (what a micro-batcher user sees, minus queueing). 40 samples
    # honestly supports a p95, not a p99 — named accordingly.
    lat_batch = min(256, batch_size)
    lat_items = [(policy_id, r) for r in requests[:lat_batch]]
    env.validate_batch(lat_items)  # warm that bucket
    latencies = []
    for _ in range(40):
        t0 = time.perf_counter()
        env.validate_batch(lat_items)
        latencies.append((time.perf_counter() - t0) * 1e3)
    latencies.sort()

    reviews_per_sec = n_requests / wall
    import math

    idx = max(0, math.ceil(0.95 * len(latencies)) - 1)
    p95_dispatch_ms = latencies[idx] if latencies else 0.0

    result = {
        "metric": "admission_reviews_per_sec_32policies",
        "value": round(reviews_per_sec, 1),
        "unit": "reviews/s/chip",
        "vs_baseline": round(reviews_per_sec / 100_000.0, 4),
        "details": {
            "n_requests": n_requests,
            "batch_size": batch_size,
            "wall_s": round(wall, 3),
            "p95_dispatch_latency_ms": round(p95_dispatch_ms, 2),
            "latency_dispatch_size": lat_batch,
            "n_policies": 32,
            "oracle_fallbacks": env.oracle_fallbacks,
        },
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
