"""Benchmark: the flagship config — 32 mixed policies, synthetic
AdmissionReview firehose (BASELINE.md config 4).

Measures the full evaluation pipeline per review (encode → batched fused
device dispatch → response materialization, i.e. everything the server does
minus HTTP framing) and prints ONE JSON line:

    {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

``vs_baseline`` is value / 100_000 — the north-star target from
BASELINE.json (the reference publishes no benchmark numbers; ≥1.0 means the
target is met on this hardware).
"""

from __future__ import annotations

import json
import sys
import time


def main() -> int:
    n_requests = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    batch_size = int(sys.argv[2]) if len(sys.argv) > 2 else 1024

    from policy_server_tpu.evaluation.environment import (
        EvaluationEnvironmentBuilder,
    )
    from policy_server_tpu.models import AdmissionReviewRequest, ValidateRequest
    from policy_server_tpu.policies.flagship import (
        flagship_policies,
        synthetic_firehose,
    )

    env = EvaluationEnvironmentBuilder(backend="jax").build(flagship_policies())

    # Pre-parse the firehose into requests (JSON/HTTP framing is out of
    # scope for this metric; a distinct corpus per request keeps the
    # encode path honest).
    docs = synthetic_firehose(n_requests, seed=42)
    requests = [
        ValidateRequest.from_admission(
            AdmissionReviewRequest.from_dict(doc).request
        )
        for doc in docs
    ]
    policy_id = "pod-security-group"  # the batcher computes ALL verdicts per
    # dispatch; target choice only affects materialization.

    # Warmup: compile the fused program for the bench bucket.
    env.warmup((batch_size,))

    latencies: list[float] = []
    t_start = time.perf_counter()
    done = 0
    while done < n_requests:
        chunk = requests[done : done + batch_size]
        t0 = time.perf_counter()
        results = env.validate_batch([(policy_id, r) for r in chunk])
        dt = time.perf_counter() - t0
        latencies.append(dt / len(chunk) * 1e3 * len(chunk))  # per-batch ms
        errors = [r for r in results if isinstance(r, Exception)]
        if errors:
            raise RuntimeError(f"bench evaluation error: {errors[0]}")
        done += len(chunk)
    wall = time.perf_counter() - t_start

    reviews_per_sec = n_requests / wall
    latencies.sort()
    p99_batch_ms = latencies[int(len(latencies) * 0.99) - 1] if latencies else 0.0

    result = {
        "metric": "admission_reviews_per_sec_32policies",
        "value": round(reviews_per_sec, 1),
        "unit": "reviews/s/chip",
        "vs_baseline": round(reviews_per_sec / 100_000.0, 4),
        "details": {
            "n_requests": n_requests,
            "batch_size": batch_size,
            "wall_s": round(wall, 3),
            "p99_batch_latency_ms": round(p99_batch_ms, 2),
            "n_policies": 32,
            "oracle_fallbacks": env.oracle_fallbacks,
        },
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
