# policy-server-tpu container image.
#
# Build args select the JAX backend wheel: the default CPU wheel serves
# the in-process test/dev loop; TPU pods install the libtpu wheel
# (requires the TPU runtime on the node, e.g. a GKE TPU nodepool).
#
# Runtime surface (reference Dockerfile parity: ports 3000/8081, non-root
# uid): API on 3000 (TLS when --cert-file/--key-file mounted), readiness +
# Prometheus /metrics on 8081.

FROM python:3.12-slim AS build

RUN apt-get update && apt-get install -y --no-install-recommends \
    g++ make && rm -rf /var/lib/apt/lists/*

ARG JAX_WHEEL="jax[cpu]"
RUN pip install --no-cache-dir \
    "${JAX_WHEEL}" aiohttp pyyaml requests cryptography prometheus_client \
    grpcio protobuf numpy

WORKDIR /src
COPY policy_server_tpu/ policy_server_tpu/
COPY csrc/ csrc/
COPY protos/ protos/
# native host encoder (ops/fastenc.py soft-fails to the Python trie if
# the extension is absent, so a failed build degrades, not breaks —
# but the failure must be VISIBLE in the build log, not swallowed)
RUN mkdir -p build && \
    { g++ -O3 -shared -fPIC -std=c++17 \
        -o build/fastenc-cpython-312-x86_64-linux-gnu.so \
        csrc/fastenc.cpp -I/usr/local/include/python3.12 \
      || echo "WARNING: fastenc build failed; Python encoder fallback"; } && \
    { g++ -O2 -shared -fPIC -std=c++17 -pthread \
        -o build/httpfront-cpython-312-x86_64-linux-gnu.so \
        csrc/httpfront.cpp \
      || echo "WARNING: httpfront build failed; --frontend native will fall back to python"; }
# native TLS termination dlopens libssl/libcrypto at RUNTIME (no
# OpenSSL -dev headers needed at build time); python:3.12-slim ships
# libssl3, so prove it resolves in the runtime base here — if this ever
# regresses (slimmer base, removed package) the build says so instead
# of every container silently serving TLS through the aiohttp fallback
RUN python -c "import ctypes; ctypes.CDLL('libssl.so.3')" \
    || echo "WARNING: libssl.so.3 missing; native TLS will fall back to aiohttp"

# test stage: the graftcheck gate (static analysis + counter/OTLP/
# dashboard consistency + failpoint and cli-docs drift) runs against the
# exact tree being shipped. CI builds this stage first
# (`docker build --target test .`); the runtime image below does not
# inherit from it, so a skipped gate never reaches production layers.
FROM build AS test
COPY tools/ tools/
COPY tests/ tests/
COPY Makefile pytest.ini cli-docs.md kubewarden-dashboard.json ./
RUN make check
# sanitizer lane: ASan+UBSan rebuilds of the natives, differential
# corpora + structure-aware fuzzer, LSan teardown audit. Skips LOUDLY
# (grep the log for SANITIZE_TOOLCHAIN_SKIP) when the stage's toolchain
# lacks the sanitizer runtimes — never silently.
RUN make sanitize

FROM python:3.12-slim

COPY --from=build /usr/local/lib/python3.12/site-packages /usr/local/lib/python3.12/site-packages
COPY --from=build /src/policy_server_tpu /app/policy_server_tpu
COPY --from=build /src/build /app/build
# csrc must ship too: ops/fastenc.py compares the .so's mtime against the
# source before loading it (missing source would disable the native path)
COPY --from=build /src/csrc /app/csrc

WORKDIR /app
# non-root (reference runs uid 65533)
USER 65533:65533

EXPOSE 3000 8081

ENTRYPOINT ["python", "-m", "policy_server_tpu"]
CMD ["--policies", "/config/policies.yml", \
     "--policies-download-dir", "/data/policies", \
     "--compilation-cache-dir", "/data/xla-cache"]
