"""sources.yml: registry trust configuration.

Reference parity: policy-fetcher's ``Sources`` / ``read_sources_file`` as used
at src/config.rs:270-285 and sources.yml.example — ``insecure_sources`` (plain
HTTP / skip TLS verify) and ``source_authorities`` (extra CA certs per
registry, entries of type Path or Data).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Mapping

import yaml


@dataclass(frozen=True)
class SourceAuthority:
    """One CA certificate for a registry host: either a file path or inline
    PEM/DER data (sources.yml.example types ``Path`` / ``Data``)."""

    type: str  # "Path" | "Data"
    path: str | None = None
    data: str | None = None

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "SourceAuthority":
        kind = d.get("type")
        if kind == "Path":
            if not d.get("path"):
                raise ValueError("source authority of type Path requires `path`")
            return cls(type="Path", path=str(d["path"]))
        if kind == "Data":
            if not d.get("data"):
                raise ValueError("source authority of type Data requires `data`")
            return cls(type="Data", data=str(d["data"]))
        raise ValueError(f"unknown source authority type: {kind!r}")

    def pem_bytes(self) -> bytes:
        if self.type == "Data":
            assert self.data is not None
            return self.data.encode()
        assert self.path is not None
        return Path(self.path).read_bytes()


@dataclass
class Sources:
    insecure_sources: frozenset[str] = field(default_factory=frozenset)
    source_authorities: dict[str, tuple[SourceAuthority, ...]] = field(
        default_factory=dict
    )

    def is_insecure(self, host: str) -> bool:
        return host in self.insecure_sources

    def authorities_for(self, host: str) -> tuple[SourceAuthority, ...]:
        return self.source_authorities.get(host, ())

    @classmethod
    def from_dict(cls, doc: Mapping[str, Any] | None) -> "Sources":
        if doc is None:
            return cls()
        if not isinstance(doc, Mapping):
            raise ValueError("sources file must contain a mapping")
        insecure = doc.get("insecure_sources") or []
        if not isinstance(insecure, (list, tuple)):
            raise ValueError("insecure_sources must be a list")
        authorities_doc = doc.get("source_authorities") or {}
        if not isinstance(authorities_doc, Mapping):
            raise ValueError("source_authorities must be a mapping")
        authorities = {
            str(host): tuple(SourceAuthority.from_dict(a) for a in certs)
            for host, certs in authorities_doc.items()
        }
        return cls(
            insecure_sources=frozenset(str(s) for s in insecure),
            source_authorities=authorities,
        )


def read_sources_file(path: str | Path) -> Sources:
    """config.rs:270-285."""
    with open(path, "r", encoding="utf-8") as f:
        doc = yaml.safe_load(f)
    return Sources.from_dict(doc)
