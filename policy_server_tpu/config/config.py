"""Config: CLI args + env fallbacks → a validated ``Config`` struct.

Reference parity: src/config.rs —
* ``Config::from_args`` (config.rs:61-169): resolves addr/port, TLS, policy
  file paths, download dir, workers/pool_size, timeouts, feature flags.
* ``pool_size = --workers or num_cpus`` (config.rs:85-90).
* ``HOSTNAME`` from env for span fields (config.rs:24-27).
* OTLP client TLS config from OTEL_* env vars (config.rs:458-496).

TPU-native additions (no reference counterpart; SURVEY.md §7):
* ``evaluation_backend``: ``jax`` (batched TPU predicate programs) or
  ``oracle`` (host interpreter; the stand-in for the reference's wasmtime
  path and the differential-testing oracle).
* micro-batcher knobs (``max_batch_size``, ``batch_timeout_ms``) — the
  batched analog of the reference's Semaphore admission control
  (src/api/handlers.rs:256-286).
* device mesh spec (``mesh``) — e.g. ``data:8`` or ``data:4,policy:2`` —
  the scale-out axis that replaces the reference's replica-based scaling
  (SURVEY.md §2.3 last row).
"""

from __future__ import annotations

import os
import socket
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

import yaml

from policy_server_tpu.models.policy import (
    PolicyOrPolicyGroup,
    parse_policies,
)
from policy_server_tpu.config.sources import Sources, read_sources_file
from policy_server_tpu.config.verification import (
    VerificationConfig,
    read_verification_file,
)

LOG_LEVELS = ("trace", "debug", "info", "warn", "error")
LOG_FORMATS = ("text", "json", "otlp")
EVALUATION_BACKENDS = ("jax", "oracle")

DEFAULT_PORT = 3000
DEFAULT_READINESS_PORT = 8081


@dataclass(frozen=True)
class TlsConfig:
    """TLS material paths (src/config.rs TlsConfig; src/certs.rs:31).

    ``cert_file``/``key_file`` must be provided together; ``client_ca_file``
    (a list — multiple CAs supported, certs.rs:231-258) enables mTLS and
    requires TLS to be enabled.
    """

    cert_file: str | None = None
    key_file: str | None = None
    client_ca_file: tuple[str, ...] = ()

    @property
    def enabled(self) -> bool:
        return self.cert_file is not None

    @property
    def mtls_enabled(self) -> bool:
        return bool(self.client_ca_file)

    def validate(self) -> None:
        if (self.cert_file is None) != (self.key_file is None):
            raise ValueError(
                "both --cert-file and --key-file must be provided to enable TLS"
            )
        if self.client_ca_file and not self.enabled:
            raise ValueError("--client-ca-file requires --cert-file and --key-file")


@dataclass(frozen=True)
class MeshSpec:
    """Device-mesh request, e.g. ``data:8`` or ``data:4,policy:2``.

    Axis names: ``data`` shards the request batch dimension; ``policy``
    shards the loaded policy set (verdict bits all-gathered; SURVEY.md §5
    long-context row). ``auto`` sizes the data axis to ``len(jax.devices())``
    at boot.
    """

    axes: tuple[tuple[str, int], ...] = (("data", 0),)  # 0 = auto

    @classmethod
    def parse(cls, spec: str) -> "MeshSpec":
        if spec in ("auto", ""):
            return cls()
        axes: list[tuple[str, int]] = []
        for part in spec.split(","):
            name, _, size = part.partition(":")
            name = name.strip()
            if name not in ("data", "policy"):
                raise ValueError(f"unknown mesh axis {name!r} (expected data/policy)")
            try:
                n = int(size)
            except ValueError:
                raise ValueError(f"invalid mesh axis size in {part!r}") from None
            if n < 1:
                raise ValueError(f"mesh axis size must be >= 1: {part!r}")
            axes.append((name, n))
        if not axes:
            raise ValueError(f"invalid mesh spec {spec!r}")
        names = [a for a, _ in axes]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate mesh axis in {spec!r}")
        return cls(axes=tuple(axes))

    def data_size(self) -> int:
        return dict(self.axes).get("data", 1)

    def policy_size(self) -> int:
        return dict(self.axes).get("policy", 1)


def _default_pool_size() -> int:
    return os.cpu_count() or 1


_SIZE_SUFFIXES = {
    "k": 1024, "ki": 1024, "kb": 1000,
    "m": 1024**2, "mi": 1024**2, "mb": 1000**2,
    "g": 1024**3, "gi": 1024**3, "gb": 1000**3,
}


def parse_size(value) -> int:
    """Byte-size value: a plain integer, or an integer with a K/M/G
    (binary) or KB/MB/GB (decimal) suffix — ``--verdict-cache-size 64M``.
    Case-insensitive; a trailing 'i' (Ki/Mi/Gi) is the same binary unit."""
    if isinstance(value, int):
        return value
    s = str(value).strip().lower()
    for suffix in sorted(_SIZE_SUFFIXES, key=len, reverse=True):
        if s.endswith(suffix):
            return int(float(s[: -len(suffix)]) * _SIZE_SUFFIXES[suffix])
    return int(s)


@dataclass
class Config:
    """The resolved server configuration (reference Config, config.rs:29-52)."""

    addr: str = "0.0.0.0"
    port: int = DEFAULT_PORT
    readiness_probe_port: int = DEFAULT_READINESS_PORT
    tls_config: TlsConfig = field(default_factory=TlsConfig)
    policies: dict[str, PolicyOrPolicyGroup] = field(default_factory=dict)
    policies_download_dir: str = "."
    sources: Sources | None = None
    verification_config: VerificationConfig | None = None
    pool_size: int = field(default_factory=_default_pool_size)
    policy_timeout_seconds: float = 2.0  # cli.rs:164-169 default 2 s
    disable_timeout_protection: bool = False
    ignore_kubernetes_connection_failure: bool = False
    kube_insecure_skip_tls_verify: bool = False
    always_accept_admission_reviews_on_namespace: str | None = None
    continue_on_errors: bool = False
    enable_metrics: bool = False
    enable_pprof: bool = False
    log_level: str = "info"
    log_fmt: str = "text"
    log_no_color: bool = False
    daemon: bool = False
    daemon_pid_file: str = "policy-server.pid"
    daemon_stdout_file: str | None = None
    daemon_stderr_file: str | None = None
    docker_config_json_path: str | None = None
    sigstore_cache_dir: str = "sigstore-data"
    hostname: str = field(default_factory=socket.gethostname)
    # --- TPU-native additions -------------------------------------------
    evaluation_backend: str = "jax"
    max_batch_size: int = 128
    batch_timeout_ms: float = 1.0
    # latency fast-path: micro-batches ≤ this size are answered by the
    # bit-exact host oracle instead of paying a device round-trip
    host_fastpath_threshold: int = 64
    # bit-exact two-tier verdict cache / in-batch row dedup budget in
    # BYTES (round 6: was rows — split between the pre-encode blob tier
    # and the post-encode row tier, evaluation/verdict_cache.py; the CLI
    # accepts K/M/G[i] suffixes via parse_size). 0 disables.
    verdict_cache_size: int = 256 * 1024 * 1024
    # soft per-request latency target (ms) for deadline-aware routing:
    # a batch whose measured device RTT estimate would exceed the oldest
    # request's remaining budget is answered host-side; ≤0 disables
    latency_budget_ms: float = 50.0
    # propagated per-request deadline (the webhook timeoutSeconds model):
    # requests that cannot meet it are shed at admission (429 +
    # Retry-After) and rows already past it are dropped pre-encode;
    # 0 disables deadline propagation and shedding
    request_timeout_ms: float = 10000.0
    # device circuit breaker: N failures within the window trip a shard
    # to the host-oracle fallback; after the cooldown a half-open probe
    # decides recovery
    breaker_failure_threshold: int = 5
    breaker_window_seconds: float = 30.0
    breaker_cooldown_seconds: float = 5.0
    # what to serve while EVERY shard's breaker is tripped:
    # oracle (bit-exact host verdicts) | monitor (accept-all) | reject (503)
    degraded_mode: str = "oracle"
    # columnar device transport (round 12): ship encoded batches as
    # bit-packed / dictionary-narrowed column planes with all-zero
    # columns elided; False restores the row-packed transport
    columnar: bool = True
    # donate columnar input buffers on dispatch (jax donate_argnums)
    donate_buffers: bool = True
    # predicate-program optimizer (round 15, ops/optimizer.py):
    # cross-policy CSE + constant folding + dead-field/mask pruning
    # before lowering; False restores the naive per-policy lowering
    predicate_opt: bool = True
    # device kernel form: 'xla' (fused jit program) or 'pallas' (fused
    # gather→predicate→reduce Pallas kernel for hot schema buckets;
    # real Mosaic lowering behind a loud capability probe, interpret
    # mode elsewhere)
    kernel: str = "xla"
    # zero-downtime policy lifecycle (lifecycle.py): 'auto' promotes a
    # canaried candidate epoch automatically, 'manual' stages it for an
    # explicit POST /policies/promote, 'off' restores the frozen-at-boot
    # policy set (no watcher, no admin endpoints, no SIGHUP reload)
    policy_reload_mode: str = "auto"
    # shadow-canary replay budget: ring-buffer capacity of recently
    # served requests (plus one synthetic review per candidate policy)
    reload_canary_requests: int = 64
    # fraction of canary replays allowed to diverge from the host oracle
    # before the candidate epoch is rejected (0.0 = any divergence
    # rejects)
    reload_divergence_threshold: float = 0.0
    # bearer token for POST /policies/reload|promote|rollback on the
    # readiness port; None disables the admin endpoints
    reload_admin_token: str | None = None
    # the on-disk policies file backing hot reload (None when the config
    # was built programmatically — reloads then reuse the in-memory set)
    policies_path: str | None = None
    # multi-tenant serving (round 16, tenancy.py): the tenants manifest
    # path and its parsed form (tenancy.TenantManifest) — each named
    # tenant gets its own policies file, epoch lifecycle, admission
    # quota, deadline class, and breaker/degraded-mode; None keeps the
    # single-tenant topology bit-identical to round 15
    tenants_path: str | None = None
    tenants: Any = None
    # background audit scanner (audit/scanner.py): 'interval' sweeps the
    # dirty set on a cadence AND fully on every epoch promotion,
    # 'on-promote' sweeps fully on epoch flips only, 'off' disables the
    # scanner (the reference's external-companion model)
    audit_mode: str = "off"
    # dirty-sweep cadence for --audit-mode interval
    audit_interval_seconds: float = 30.0
    # rows per best-effort audit-lane batch
    audit_batch_size: int = 256
    # byte budget of the audit snapshot store (LRU-evicted beyond it)
    audit_max_snapshot_bytes: int = 64 * 1024 * 1024
    # optional YAML/JSON resources file seeding the snapshot store at
    # boot (the stand-in for the companion scanner's cluster LIST)
    audit_resources_file: str | None = None
    # live-cluster watch feed (audit/watch_feed.py, round 13): list+watch
    # events populate the audit snapshot store directly, so the scanner
    # audits the LIVE cluster instead of only /validate traffic + a seed
    # file; requires --audit-mode != off
    audit_watch: bool = False
    # apiVersion/Kind list the watch feed follows
    audit_watch_resources: str = (
        "v1/Pod,v1/Namespace,apps/v1/Deployment,apps/v1/ReplicaSet,"
        "apps/v1/StatefulSet,apps/v1/DaemonSet"
    )
    # bounded watch-event queue between the per-kind watcher threads and
    # the snapshot applier; overflow drops the event (counted) and
    # forces a full re-LIST resync of that kind
    audit_watch_max_queue_events: int = 65536
    # persistent (object × policy) verdict matrix (round 23,
    # audit/matrix.py): sweeps evaluate only the dirty cross-product,
    # verdict changes stream on GET /audit/stream, columns spill through
    # the statestore for warm resume, and byte-identical /validate
    # UPDATEs answer from precomputed verdicts; requires the scanner
    audit_matrix: bool = False
    # concurrent GET /audit/stream clients (beyond it: in-band 503)
    audit_stream_max_clients: int = 64
    # matrix spill cadence (scanner-driven, rides the sweep tail)
    audit_matrix_spill_seconds: float = 30.0
    # stretch: evaluate a CANDIDATE epoch's changed columns against the
    # live snapshot during shadow canary and surface the cluster-wide
    # what-if diff on the reload status
    audit_matrix_whatif: bool = False
    # native-frontend connection-abuse hardening (csrc/httpfront.cpp,
    # round 13): idle keep-alive reap, per-request read (arrival)
    # timeout bounding slowloris drips, and the concurrent-connection
    # cap answering an in-band 503 over it (0 disables each)
    native_idle_timeout_seconds: float = 75.0
    native_read_timeout_seconds: float = 30.0
    native_max_connections: int = 0
    # native TLS termination (round 20): 'auto' terminates TLS on the
    # C++ epoll loops when --cert/--key are set and libssl loads
    # (hot-rotation swaps the SSL_CTX for new connections; established
    # ones drain on the old identity), falling back LOUDLY to the
    # aiohttp TLS frontend when libssl is unavailable; 'off' keeps
    # aiohttp terminating TLS even under --frontend native
    native_tls: str = "auto"
    # native TLS handshake-arrival bound: the full handshake must
    # COMPLETE within this window measured from accept — byte drips
    # never refresh it (slowloris at the TLS layer); 0 disables
    native_tls_handshake_timeout_seconds: float = 10.0
    # durable last-good state store (round 17, statestore.py): the
    # crash-tolerance directory holding the content-addressed policy
    # artifact cache, the per-tenant last-good epoch manifests, and the
    # audit snapshot spill — a warm boot loads pinned artifacts with
    # zero network, degrades loudly to last-good when fetch fails, and
    # resumes the audit watch instead of re-LISTing. None = amnesiac
    # restarts (pre-round-17 behavior)
    state_dir: str | None = None
    # audit-spill cadence: how often the watch feed spills its
    # resourceVersion cursors + snapshot inventory to the state dir
    state_audit_spill_seconds: float = 30.0
    # main-process self-heal watchdog (supervision.py): rebuild a wedged
    # batcher dispatch loop / native-frontend drainer instead of serving
    # zombies; the check cadence in seconds (0 disables)
    selfheal_interval_seconds: float = 5.0
    # host-local serving shards (round 22, runtime/shards.py): M full
    # serving stacks (each its own EvaluationEnvironment — verdict cache
    # + breaker — and MicroBatcher) behind a health/queue-depth router;
    # the promoted epoch artifacts and the XLA compilation cache are
    # shared read-only. 1 = the router is BYPASSED entirely and the
    # serving path is byte- and path-identical to previous rounds
    serving_shards: int = 1
    # shard heartbeat cadence: how often the router probes each shard's
    # dispatch loop; a wedged/dead shard is fenced within one interval
    # (queued rows re-routed to a sibling or answered 503+Retry-After)
    # and warm-revived in place without touching its siblings
    shard_heartbeat_seconds: float = 0.5
    # flight recorder (round 18, telemetry/flightrec.py): always-on
    # batch-granular phase timelines + per-phase histograms + tail
    # exemplars at <2% overhead; False disables the recorder AND the
    # GET /debug/timeline surface (the phase histogram family still
    # exports, empty)
    flight_recorder: bool = True
    # preallocated phase-event ring capacity (rounded up to a power of
    # two); at ~10 batch events per batch, the default holds the last
    # ~6.5k batches
    recorder_ring_events: int = 65536
    # fraction of delivered rows that record per-row timeline segments
    # (deterministic 1-in-round(1/rate) stride, no RNG on the serving
    # path); 0 disables row sampling (batch events and exemplars remain)
    recorder_row_sample_rate: float = 0.01
    # prefork respawn breaker: consecutive fast crash-loop deaths after
    # which a worker slot stops respawning (readiness then reports the
    # degraded slot honestly)
    worker_respawn_giveup: int = 5
    mesh: MeshSpec = field(default_factory=MeshSpec)
    # how a >1 policy axis executes (round 14): 'fused' lowers the whole
    # policy set as ONE SPMD program over the (data x policy) mesh —
    # per-shard lax.switch branches + an all-gather collective replace
    # the thread pool's N host-side joins; 'threaded' keeps the legacy
    # thread-per-shard MPMD dispatcher (parallel/policy_sharded.py)
    mesh_dispatch: str = "fused"
    warmup_at_boot: bool = True
    compilation_cache_dir: str | None = None
    # prefork HTTP frontend (runtime/frontend.py): worker processes
    # sharing the API port via SO_REUSEPORT; 1 = in-process serving
    http_workers: int = 1
    # HTTP framing implementation for the /validate|/validate_raw|/audit
    # POST surface: 'native' serves them from the GIL-free C++ epoll
    # front-end (csrc/httpfront.cpp; falls back to 'python' loudly when
    # the extension cannot build/load), 'python' keeps aiohttp framing —
    # the always-available fallback and differential correctness oracle
    frontend: str = "python"
    # context-aware snapshot freshness (see the staleness contract in
    # context/service.py): watch keeps snapshots event-fresh; the refresh
    # period bounds poll-mode staleness and watch-mode backoff/resync
    context_refresh_seconds: float = 30.0
    context_watch: bool = True
    # multi-host bring-up (SURVEY.md §7.2 step 10): when the coordinator is
    # set, bootstrap calls jax.distributed.initialize before mesh build so
    # the mesh spans every process's devices (ICI in-slice, DCN across)
    distributed_coordinator: str | None = None
    distributed_num_processes: int | None = None
    distributed_process_id: int | None = None

    def validate(self) -> None:
        self.tls_config.validate()
        if self.log_level not in LOG_LEVELS:
            raise ValueError(f"invalid log level {self.log_level!r}")
        if self.log_fmt not in LOG_FORMATS:
            raise ValueError(f"invalid log format {self.log_fmt!r}")
        if self.evaluation_backend not in EVALUATION_BACKENDS:
            raise ValueError(
                f"invalid evaluation backend {self.evaluation_backend!r} "
                f"(expected one of {EVALUATION_BACKENDS})"
            )
        if self.pool_size < 1:
            raise ValueError("--workers must be >= 1")
        if self.max_batch_size < 1:
            raise ValueError("--max-batch-size must be >= 1")
        if 0 < self.verdict_cache_size < 1024 * 1024:
            # round 6 changed --verdict-cache-size from ROWS to BYTES; a
            # pinned pre-round-6 value like 4096 would silently collapse
            # the cache to a couple of entries — warn loudly instead of
            # degrading quietly (a sub-MiB budget is never intentional;
            # use 0 to disable caching outright)
            import logging

            logging.getLogger("kubewarden-policy-server").warning(
                "--verdict-cache-size=%d bytes is below 1 MiB — the flag "
                "changed units from rows to bytes in round 6 (suffixes "
                "accepted: 64M, 256Mi); a value this small effectively "
                "disables cross-batch dedup",
                self.verdict_cache_size,
            )
        if not (0 <= self.port <= 65535) or not (0 <= self.readiness_probe_port <= 65535):
            raise ValueError("ports must be in [0, 65535]")
        if self.context_refresh_seconds <= 0:
            raise ValueError("--context-refresh-seconds must be > 0")
        if self.breaker_failure_threshold < 1:
            raise ValueError("--breaker-failure-threshold must be >= 1")
        if self.breaker_window_seconds <= 0:
            raise ValueError("--breaker-window-seconds must be > 0")
        if self.breaker_cooldown_seconds < 0:
            raise ValueError("--breaker-cooldown-seconds must be >= 0")
        if self.degraded_mode not in ("oracle", "monitor", "reject"):
            raise ValueError(
                f"invalid degraded mode {self.degraded_mode!r} "
                "(expected oracle, monitor, or reject)"
            )
        if self.http_workers < 1:
            raise ValueError("--http-workers must be >= 1")
        if self.frontend not in ("python", "native"):
            raise ValueError(
                f"invalid frontend {self.frontend!r} "
                "(expected python or native)"
            )
        if self.policy_reload_mode not in ("off", "auto", "manual"):
            raise ValueError(
                f"invalid policy reload mode {self.policy_reload_mode!r} "
                "(expected off, auto, or manual)"
            )
        if self.reload_canary_requests < 0:
            raise ValueError("--reload-canary-requests must be >= 0")
        if self.audit_mode not in ("off", "interval", "on-promote"):
            raise ValueError(
                f"invalid audit mode {self.audit_mode!r} "
                "(expected off, interval, or on-promote)"
            )
        if self.audit_interval_seconds <= 0:
            raise ValueError("--audit-interval-seconds must be > 0")
        if self.audit_batch_size < 1:
            raise ValueError("--audit-batch-size must be >= 1")
        if self.audit_max_snapshot_bytes < 0:
            raise ValueError("--audit-max-snapshot-bytes must be >= 0")
        if self.audit_watch:
            if self.audit_mode == "off":
                raise ValueError(
                    "--audit-watch requires the audit scanner "
                    "(--audit-mode interval or on-promote)"
                )
            from policy_server_tpu.audit.watch_feed import (
                parse_watch_resources,
            )

            if not parse_watch_resources(self.audit_watch_resources):
                raise ValueError(
                    "--audit-watch-resources must name at least one "
                    "apiVersion/Kind"
                )
        if self.audit_watch_max_queue_events < 1:
            raise ValueError(
                "--audit-watch-max-queue-events must be >= 1"
            )
        if self.audit_matrix and self.audit_mode == "off":
            raise ValueError(
                "--audit-matrix requires the audit scanner "
                "(--audit-mode interval or on-promote)"
            )
        if self.audit_stream_max_clients < 1:
            raise ValueError("--audit-stream-max-clients must be >= 1")
        if self.audit_matrix_spill_seconds <= 0:
            raise ValueError("--audit-matrix-spill-seconds must be > 0")
        if self.audit_matrix_whatif and not self.audit_matrix:
            raise ValueError(
                "--audit-matrix-whatif requires --audit-matrix"
            )
        if self.state_audit_spill_seconds <= 0:
            raise ValueError("--state-audit-spill-seconds must be > 0")
        if self.selfheal_interval_seconds < 0:
            raise ValueError("--selfheal-interval-seconds must be >= 0")
        if self.serving_shards < 1:
            raise ValueError("--serving-shards must be >= 1")
        if self.shard_heartbeat_seconds <= 0:
            raise ValueError("--shard-heartbeat-seconds must be > 0")
        if self.worker_respawn_giveup < 1:
            raise ValueError("--worker-respawn-giveup must be >= 1")
        if self.native_idle_timeout_seconds < 0:
            raise ValueError("--native-idle-timeout-seconds must be >= 0")
        if self.native_read_timeout_seconds < 0:
            raise ValueError("--native-read-timeout-seconds must be >= 0")
        if self.native_max_connections < 0:
            raise ValueError("--native-max-connections must be >= 0")
        if self.native_tls not in ("auto", "off"):
            raise ValueError(
                f"invalid native TLS mode {self.native_tls!r} "
                "(expected auto or off)"
            )
        if self.native_tls_handshake_timeout_seconds < 0:
            raise ValueError(
                "--native-tls-handshake-timeout-seconds must be >= 0"
            )
        if not (0.0 <= self.reload_divergence_threshold <= 1.0):
            raise ValueError(
                "--reload-divergence-threshold must be in [0, 1]"
            )
        if self.tenants is not None:
            from policy_server_tpu.tenancy import TenantManifest

            if not isinstance(self.tenants, TenantManifest):
                raise ValueError(
                    "config.tenants must be a tenancy.TenantManifest "
                    "(use read_tenants_file)"
                )
        if self.mesh_dispatch not in ("fused", "threaded"):
            raise ValueError(
                f"invalid mesh dispatch {self.mesh_dispatch!r} "
                "(expected 'fused' or 'threaded')"
            )
        if self.distributed_coordinator is None:
            if (
                self.distributed_num_processes is not None
                or self.distributed_process_id is not None
            ):
                raise ValueError(
                    "--distributed-num-processes/--distributed-process-id "
                    "require --distributed-coordinator"
                )
        else:
            if (self.distributed_num_processes is None) != (
                self.distributed_process_id is None
            ):
                raise ValueError(
                    "--distributed-num-processes and --distributed-process-id "
                    "must be set together"
                )
            if (
                self.distributed_num_processes is not None
                and not (
                    0
                    <= self.distributed_process_id
                    < self.distributed_num_processes
                )
            ):
                raise ValueError(
                    "--distributed-process-id must be in "
                    "[0, --distributed-num-processes)"
                )

    @property
    def policy_timeout(self) -> float | None:
        """Effective evaluation deadline in seconds, None when disabled
        (reference: --disable-timeout-protection, cli.rs:164-176)."""
        return None if self.disable_timeout_protection else self.policy_timeout_seconds

    @classmethod
    def from_args(cls, args: Any) -> "Config":
        """Build a Config from a parsed argparse namespace
        (reference Config::from_args, config.rs:61-169)."""
        policies_path = Path(args.policies)
        policies = read_policies_file(policies_path) if policies_path.exists() else {}
        if not policies_path.exists() and not getattr(args, "allow_missing_policies", False):
            raise FileNotFoundError(f"policies file not found: {policies_path}")

        sources = read_sources_file(args.sources_path) if args.sources_path else None
        verification = (
            read_verification_file(args.verification_path)
            if args.verification_path
            else None
        )

        tls = TlsConfig(
            cert_file=args.cert_file,
            key_file=args.key_file,
            client_ca_file=tuple(args.client_ca_file or ()),
        )

        cfg = cls(
            addr=args.addr,
            port=args.port,
            readiness_probe_port=args.readiness_probe_port,
            tls_config=tls,
            policies=policies,
            policies_download_dir=args.policies_download_dir,
            sources=sources,
            verification_config=verification,
            pool_size=args.workers if args.workers else _default_pool_size(),
            policy_timeout_seconds=float(args.policy_timeout),
            disable_timeout_protection=args.disable_timeout_protection,
            ignore_kubernetes_connection_failure=args.ignore_kubernetes_connection_failure,
            kube_insecure_skip_tls_verify=args.kube_insecure_skip_tls_verify,
            always_accept_admission_reviews_on_namespace=(
                args.always_accept_admission_reviews_on_namespace or None
            ),
            continue_on_errors=args.continue_on_errors,
            enable_metrics=args.enable_metrics,
            enable_pprof=args.enable_pprof,
            log_level=args.log_level,
            log_fmt=args.log_fmt,
            log_no_color=args.log_no_color,
            daemon=args.daemon,
            daemon_pid_file=args.daemon_pid_file,
            daemon_stdout_file=args.daemon_stdout_file,
            daemon_stderr_file=args.daemon_stderr_file,
            docker_config_json_path=args.docker_config_json_path,
            sigstore_cache_dir=args.sigstore_cache_dir,
            evaluation_backend=args.evaluation_backend,
            max_batch_size=args.max_batch_size,
            batch_timeout_ms=float(args.batch_timeout_ms),
            host_fastpath_threshold=int(args.host_fastpath_threshold),
            verdict_cache_size=parse_size(args.verdict_cache_size),
            latency_budget_ms=float(args.latency_budget_ms),
            request_timeout_ms=float(args.request_timeout_ms),
            breaker_failure_threshold=int(args.breaker_failure_threshold),
            breaker_window_seconds=float(args.breaker_window_seconds),
            breaker_cooldown_seconds=float(args.breaker_cooldown_seconds),
            columnar=args.columnar == "on",
            donate_buffers=args.donate_buffers == "on",
            predicate_opt=args.predicate_opt == "on",
            kernel=args.kernel,
            degraded_mode=args.degraded_mode,
            policy_reload_mode=args.policy_reload_mode,
            reload_canary_requests=int(args.reload_canary_requests),
            reload_divergence_threshold=float(
                args.reload_divergence_threshold
            ),
            reload_admin_token=args.reload_admin_token or None,
            policies_path=str(policies_path) if policies_path.exists() else None,
            tenants_path=args.tenants or None,
            tenants=_read_tenants(args.tenants),
            audit_mode=args.audit_mode,
            audit_interval_seconds=float(args.audit_interval_seconds),
            audit_batch_size=int(args.audit_batch_size),
            audit_max_snapshot_bytes=parse_size(args.audit_max_snapshot_bytes),
            audit_resources_file=args.audit_resources_file or None,
            audit_watch=args.audit_watch,
            audit_watch_resources=args.audit_watch_resources,
            audit_watch_max_queue_events=int(
                args.audit_watch_max_queue_events
            ),
            audit_matrix=args.audit_matrix,
            audit_stream_max_clients=int(args.audit_stream_max_clients),
            audit_matrix_spill_seconds=float(
                args.audit_matrix_spill_seconds
            ),
            audit_matrix_whatif=args.audit_matrix_whatif,
            native_idle_timeout_seconds=float(
                args.native_idle_timeout_seconds
            ),
            native_read_timeout_seconds=float(
                args.native_read_timeout_seconds
            ),
            native_max_connections=int(args.native_max_connections),
            native_tls=getattr(args, "native_tls", "auto"),
            native_tls_handshake_timeout_seconds=float(
                getattr(args, "native_tls_handshake_timeout_seconds", 10.0)
            ),
            state_dir=args.state_dir or None,
            state_audit_spill_seconds=float(args.state_audit_spill_seconds),
            selfheal_interval_seconds=float(args.selfheal_interval_seconds),
            serving_shards=int(getattr(args, "serving_shards", 1)),
            shard_heartbeat_seconds=float(
                getattr(args, "shard_heartbeat_seconds", 0.5)
            ),
            flight_recorder=args.flight_recorder == "on",
            recorder_ring_events=int(args.recorder_ring_events),
            recorder_row_sample_rate=float(args.recorder_row_sample_rate),
            worker_respawn_giveup=int(args.worker_respawn_giveup),
            mesh=MeshSpec.parse(args.mesh),
            mesh_dispatch=args.mesh_dispatch,
            warmup_at_boot=not args.no_warmup,
            compilation_cache_dir=args.compilation_cache_dir,
            http_workers=int(args.http_workers),
            frontend=args.frontend,
            context_refresh_seconds=float(args.context_refresh_seconds),
            context_watch=not args.context_no_watch,
            distributed_coordinator=args.distributed_coordinator,
            distributed_num_processes=args.distributed_num_processes,
            distributed_process_id=args.distributed_process_id,
        )
        cfg.validate()
        return cfg


def _read_tenants(path: str | None):
    """Parse the --tenants manifest (None passthrough)."""
    if not path:
        return None
    from policy_server_tpu.tenancy import read_tenants_file

    return read_tenants_file(path)


def read_policies_file(path: str | Path) -> dict[str, PolicyOrPolicyGroup]:
    """config.rs:449-453 + parse (config.rs:219-258)."""
    return read_policies_source(path)[0]


def read_policies_source(
    path: str | Path,
) -> tuple[dict[str, PolicyOrPolicyGroup], str]:
    """Read + parse a policies file, returning the parsed mapping AND
    the exact text it was parsed from — the durable-manifest path
    (round 17) persists the bytes that were actually compiled/canaried,
    never a re-read that could have changed underneath the reload."""
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    return parse_policies(yaml.safe_load(text)), text


def build_client_tls_config_from_env(prefix: str = "OTEL_EXPORTER_OTLP") -> dict[str, str]:
    """OTLP exporter TLS settings from env (config.rs:458-496):
    ``{prefix}_CERTIFICATE`` (CA), ``{prefix}_CLIENT_CERTIFICATE``,
    ``{prefix}_CLIENT_KEY``. Either all client vars set or none."""
    ca = os.environ.get(f"{prefix}_CERTIFICATE")
    cert = os.environ.get(f"{prefix}_CLIENT_CERTIFICATE")
    key = os.environ.get(f"{prefix}_CLIENT_KEY")
    out: dict[str, str] = {}
    if ca:
        out["ca_file"] = ca
    if (cert is None) != (key is None):
        raise ValueError(
            f"{prefix}_CLIENT_CERTIFICATE and {prefix}_CLIENT_KEY must be set together"
        )
    if cert and key:
        out["cert_file"] = cert
        out["key_file"] = key
    return out
