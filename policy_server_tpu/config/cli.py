"""CLI definition with per-flag ``KUBEWARDEN_*`` env fallbacks.

Reference parity: src/cli.rs — every flag has an env-var fallback
(cli.rs:24-212); ``--long-version`` prints the builtins banner (cli.rs:7-21,
here: the predicate-IR op registry instead of OPA builtins); the ``docs``
subcommand regenerates the markdown CLI reference (src/main.rs:68,
cli-docs.md), and CI can diff it for freshness.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Any, Sequence

from policy_server_tpu.version import __version__

PROG = "policy-server-tpu"


def _env(name: str, default: Any = None) -> Any:
    return os.environ.get(name, default)


def _env_flag(name: str) -> bool:
    v = os.environ.get(name, "")
    return v.lower() in ("1", "true", "yes", "on")


# (flag, env, kwargs) — single source of truth for the parser and for docs.
def _flag_specs() -> list[tuple[str, str | None, dict[str, Any]]]:
    return [
        ("--addr", "KUBEWARDEN_BIND_ADDRESS",
         dict(default="0.0.0.0", metavar="BIND_ADDRESS",
              help="Bind against ADDRESS")),
        ("--port", "KUBEWARDEN_PORT",
         dict(type=int, default=3000, metavar="PORT",
              help="Listen on PORT")),
        ("--readiness-probe-port", "KUBEWARDEN_READINESS_PROBE_PORT",
         dict(type=int, default=8081, metavar="READINESS_PROBE_PORT",
              help="Expose the readiness endpoint on this (plaintext) port")),
        ("--policies", "KUBEWARDEN_POLICIES",
         dict(default="policies.yml", metavar="POLICIES_FILE",
              help="YAML file holding the policies to be loaded and their settings")),
        ("--policies-download-dir", "KUBEWARDEN_POLICIES_DOWNLOAD_DIR",
         dict(default=".", metavar="POLICIES_DOWNLOAD_DIR",
              help="Download path for the policies")),
        ("--sources-path", "KUBEWARDEN_SOURCES_PATH",
         dict(default=None, metavar="SOURCES_PATH",
              help="YAML file holding source information (registries, HTTP, "
                   "insecure sources, authorities)")),
        ("--verification-path", "KUBEWARDEN_VERIFICATION_CONFIG_PATH",
         dict(default=None, metavar="VERIFICATION_CONFIG_PATH",
              help="YAML file holding verification config information "
                   "(signatures, requirements)")),
        ("--sigstore-cache-dir", "KUBEWARDEN_SIGSTORE_CACHE_DIR",
         dict(default="sigstore-data", metavar="SIGSTORE_CACHE_DIR",
              help="Directory used to cache sigstore data")),
        ("--docker-config-json-path", "KUBEWARDEN_DOCKER_CONFIG_JSON_PATH",
         dict(default=None, metavar="DOCKER_CONFIG",
              help="Path to a Docker config.json-like file holding registry "
                   "authentication details")),
        ("--cert-file", "KUBEWARDEN_CERT_FILE",
         dict(default=None, metavar="CERT_FILE",
              help="Path to an X.509 certificate file for HTTPS")),
        ("--key-file", "KUBEWARDEN_KEY_FILE",
         dict(default=None, metavar="KEY_FILE",
              help="Path to an X.509 private key file for HTTPS")),
        ("--client-ca-file", "KUBEWARDEN_CLIENT_CA_FILE",
         dict(default=None, metavar="CLIENT_CA_FILE", action="append",
              help="Path to a CA certificate file that issued the client "
                   "certificates; required to enable mTLS (repeatable)")),
        ("--workers", "KUBEWARDEN_WORKERS",
         dict(type=int, default=None, metavar="WORKERS_NUMBER",
              help="Number of concurrent evaluation slots (default: number of CPUs); "
                   "bounds in-flight micro-batches in the TPU backend")),
        ("--policy-timeout", "KUBEWARDEN_POLICY_TIMEOUT",
         dict(type=float, default=2.0, metavar="MAXIMUM_EXECUTION_TIME_SECONDS",
              help="Interrupt policy evaluation after the given time")),
        ("--request-timeout-ms", "KUBEWARDEN_REQUEST_TIMEOUT_MS",
         dict(type=float, default=10000.0, metavar="MS",
              help="Propagated per-request deadline, aligned to the "
                   "admission webhook timeoutSeconds model (the API server "
                   "abandons a review after its timeout, so work past it is "
                   "waste). Requests whose estimated queue wait exceeds the "
                   "budget are shed at admission with 429 + Retry-After, "
                   "and rows already expired when their batch forms are "
                   "dropped before encode/dispatch. 0 disables deadline "
                   "propagation and load shedding")),
        ("--disable-timeout-protection", "KUBEWARDEN_DISABLE_TIMEOUT_PROTECTION",
         dict(action="store_true", help="Disable policy timeout protection")),
        ("--ignore-kubernetes-connection-failure",
         "KUBEWARDEN_IGNORE_KUBERNETES_CONNECTION_FAILURE",
         dict(action="store_true",
              help="Do not exit with an error if the Kubernetes connection fails; "
                   "context-aware policies will break")),
        ("--kube-insecure-skip-tls-verify",
         "KUBEWARDEN_KUBE_INSECURE_SKIP_TLS_VERIFY",
         dict(action="store_true",
              help="Skip TLS verification of the Kubernetes API server "
                   "(explicit opt-in; without it, a missing cluster CA falls "
                   "back to the system trust store)")),
        ("--always-accept-admission-reviews-on-namespace",
         "KUBEWARDEN_ALWAYS_ACCEPT_ADMISSION_REVIEWS_ON_NAMESPACE",
         dict(default=None, metavar="NAMESPACE",
              help="Always accept AdmissionReviews that target the given namespace")),
        ("--continue-on-errors", "KUBEWARDEN_CONTINUE_ON_ERRORS",
         dict(action="store_true", help=argparse.SUPPRESS)),  # hidden (cli.rs:207-211)
        ("--enable-metrics", "KUBEWARDEN_ENABLE_METRICS",
         dict(action="store_true", help="Enable OTLP metrics")),
        ("--enable-pprof", "KUBEWARDEN_ENABLE_PPROF",
         dict(action="store_true", help="Enable profiling endpoints")),
        ("--log-level", "KUBEWARDEN_LOG_LEVEL",
         dict(default="info", metavar="LOG_LEVEL",
              choices=["trace", "debug", "info", "warn", "error"],
              help="Log level (trace, debug, info, warn, error)")),
        ("--log-fmt", "KUBEWARDEN_LOG_FMT",
         dict(default="text", metavar="LOG_FMT", choices=["text", "json", "otlp"],
              help="Log output format (text, json, otlp)")),
        ("--log-no-color", "KUBEWARDEN_LOG_NO_COLOR",
         dict(action="store_true", help="Disable colored output for logs")),
        ("--daemon", "KUBEWARDEN_DAEMON",
         dict(action="store_true",
              help="If set, runs policy-server in detached mode as a daemon")),
        ("--daemon-pid-file", "KUBEWARDEN_DAEMON_PID_FILE",
         dict(default="policy-server.pid", metavar="DAEMON-PID-FILE",
              help="Path to the PID file, used only when running in daemon mode")),
        ("--daemon-stdout-file", "KUBEWARDEN_DAEMON_STDOUT_FILE",
         dict(default=None, metavar="DAEMON-STDOUT-FILE",
              help="Path to the file holding stdout, used only in daemon mode")),
        ("--daemon-stderr-file", "KUBEWARDEN_DAEMON_STDERR_FILE",
         dict(default=None, metavar="DAEMON-STDERR-FILE",
              help="Path to the file holding stderr, used only in daemon mode")),
        # --- TPU-native flags (no reference counterpart; SURVEY.md §7) ----
        ("--evaluation-backend", "KUBEWARDEN_EVALUATION_BACKEND",
         dict(default="jax", metavar="BACKEND", choices=["jax", "oracle"],
              help="Evaluation backend: 'jax' (batched TPU predicate programs) "
                   "or 'oracle' (host interpreter, the differential-test oracle)")),
        ("--max-batch-size", "KUBEWARDEN_MAX_BATCH_SIZE",
         dict(type=int, default=128, metavar="N",
              help="Maximum micro-batch size dispatched to the device")),
        ("--batch-timeout-ms", "KUBEWARDEN_BATCH_TIMEOUT_MS",
         dict(type=float, default=1.0, metavar="MS",
              help="Maximum time a request waits for its micro-batch to fill")),
        ("--host-fastpath-threshold", "KUBEWARDEN_HOST_FASTPATH_THRESHOLD",
         dict(type=int, default=64, metavar="N",
              help="Micro-batches with at most N requests are answered by "
                   "the bit-exact host oracle instead of a device dispatch "
                   "(latency fast-path; 0 disables)")),
        ("--latency-budget-ms", "KUBEWARDEN_LATENCY_BUDGET_MS",
         dict(type=float, default=50.0, metavar="MS",
              help="Soft per-request latency target for deadline-aware "
                   "routing: when the measured device round-trip estimate "
                   "would exceed the oldest queued request's remaining "
                   "budget, the batch is answered by the bit-exact host "
                   "oracle instead (0 disables; distinct from "
                   "--policy-timeout, the hard in-band deadline)")),
        ("--columnar", "KUBEWARDEN_COLUMNAR",
         dict(default="on", metavar="MODE", choices=["on", "off"],
              help="Columnar device transport (round 12): ship encoded "
                   "batches as bit-packed / dictionary-narrowed column "
                   "PLANES with all-zero columns elided (steady-state "
                   "traffic ships only delta columns; elided planes are "
                   "reconstructed from device-resident zero constants). "
                   "'off' restores the row-packed transport. Mesh-sharded "
                   "programs always use the packed transport")),
        ("--donate-buffers", "KUBEWARDEN_DONATE_BUFFERS",
         dict(default="on", metavar="MODE", choices=["on", "off"],
              help="Donate columnar input buffers on dispatch "
                   "(jax donate_argnums) so the device transport does not "
                   "round-trip dead input buffers; 'off' disables "
                   "donation (diagnostic)")),
        ("--predicate-opt", "KUBEWARDEN_PREDICATE_OPT",
         dict(default="on", metavar="MODE", choices=["on", "off"],
              help="Predicate-program optimizer (round 15): before "
                   "lowering, run cross-policy common-subexpression "
                   "elimination (identical field-gather + comparison "
                   "subtrees compute once via a shared let-binding "
                   "table), constant folding (whole policies folding to "
                   "a constant verdict drop out of the device program), "
                   "and dead-field pruning (fields no surviving "
                   "predicate reads lose their gather columns; validity "
                   "masks provably redundant at the zero-fill lose "
                   "their mask lanes). Purely structural — bit-exact vs "
                   "the unoptimized program and the host oracle. 'off' "
                   "restores the naive per-policy lowering")),
        ("--kernel", "KUBEWARDEN_KERNEL",
         dict(default="xla", metavar="KERNEL", choices=["xla", "pallas"],
              help="Device kernel form for the fused predicate program: "
                   "'xla' (default) lowers through XLA; 'pallas' streams "
                   "packed rows through a fused gather→predicate→reduce "
                   "Pallas kernel in VMEM-resident (row × policy) tiles "
                   "for schema buckets that turn hot (per-bucket opt-in "
                   "by dispatch count). The real Mosaic lowering is "
                   "gated behind a LOUD capability probe; where it "
                   "cannot compile (CPU dev boxes) the kernel runs in "
                   "interpret mode — bit-exact, slow, warned once. "
                   "Armed buckets use the packed transport (the "
                   "kernel fuses the unpack; columnar delta planes "
                   "keep the XLA path)")),
        ("--breaker-failure-threshold", "KUBEWARDEN_BREAKER_FAILURE_THRESHOLD",
         dict(type=int, default=5, metavar="N",
              help="Device circuit breaker: dispatch faults / watchdog "
                   "trips within the window that trip a shard OPEN (its "
                   "traffic then serves from the bit-exact host oracle "
                   "until a half-open probe succeeds)")),
        ("--breaker-window-seconds", "KUBEWARDEN_BREAKER_WINDOW_SECONDS",
         dict(type=float, default=30.0, metavar="SECONDS",
              help="Device circuit breaker: sliding window over which "
                   "failures accumulate toward the trip threshold")),
        ("--breaker-cooldown-seconds", "KUBEWARDEN_BREAKER_COOLDOWN_SECONDS",
         dict(type=float, default=5.0, metavar="SECONDS",
              help="Device circuit breaker: time a tripped shard stays "
                   "OPEN before a half-open recovery probe is admitted")),
        ("--degraded-mode", "KUBEWARDEN_DEGRADED_MODE",
         dict(default="oracle", metavar="MODE",
              choices=["oracle", "monitor", "reject"],
              help="What to serve while EVERY device shard's breaker is "
                   "tripped: 'oracle' keeps serving bit-exact host-oracle "
                   "verdicts (default), 'monitor' serves accept-all "
                   "monitor-mode verdicts (fail-open), 'reject' answers "
                   "in-band 503s (fail-closed)")),
        ("--verdict-cache-size", "KUBEWARDEN_VERDICT_CACHE_SIZE",
         dict(default="256Mi", metavar="BYTES",
              help="Byte budget of the bit-exact two-tier verdict cache "
                   "(accepts K/M/G[i] suffixes; was rows before round 6). "
                   "Split between a pre-encode blob tier (exact payload "
                   "replays skip encoding) and a post-encode row tier "
                   "(uid/name-varying duplicates collapse after encode): "
                   "identical (policy, payload) rows are answered without "
                   "re-dispatch (policy evaluation is a pure function of "
                   "the payload, so this is lossless; wasm-backed verdicts "
                   "are never cached). Size it to hold the live admission "
                   "template working set — the default 256Mi holds tens of "
                   "thousands of templates. 0 disables caching AND "
                   "in-batch row dedup")),
        ("--policy-reload-mode", "KUBEWARDEN_POLICY_RELOAD_MODE",
         dict(default="auto", metavar="MODE",
              choices=["off", "auto", "manual"],
              help="Zero-downtime policy hot reload (epoch-based, "
                   "lifecycle.py): 'auto' fetches+compiles+warms a new "
                   "policy set in the background on SIGHUP / policies-file "
                   "change / POST /policies/reload, shadow-canaries it "
                   "against the host oracle, and promotes atomically only "
                   "on a clean canary (last-good keeps serving otherwise); "
                   "'manual' stages the validated candidate for an "
                   "explicit POST /policies/promote; 'off' freezes the "
                   "policy set at boot (pre-round-9 behavior)")),
        ("--reload-canary-requests", "KUBEWARDEN_RELOAD_CANARY_REQUESTS",
         dict(type=int, default=64, metavar="N",
              help="Shadow-canary replay budget: the candidate epoch "
                   "replays up to N recently served requests (a bounded "
                   "ring recorded at dispatch, plus one synthetic review "
                   "per candidate policy) and cross-checks every verdict "
                   "against the host oracle before promotion")),
        ("--reload-divergence-threshold",
         "KUBEWARDEN_RELOAD_DIVERGENCE_THRESHOLD",
         dict(type=float, default=0.0, metavar="FRACTION",
              help="Fraction of canary replays allowed to diverge from "
                   "the host oracle before the candidate policy set is "
                   "rejected (default 0.0: any divergence, trap, or "
                   "canary timeout keeps last-good serving and increments "
                   "the rollback counter)")),
        ("--audit-mode", "KUBEWARDEN_AUDIT_MODE",
         dict(default="off", metavar="MODE",
              choices=["off", "interval", "on-promote"],
              help="Background audit scanner (audit/scanner.py): "
                   "continuously re-scans a snapshot of cluster resources "
                   "(seeded from --audit-resources-file and from every "
                   "object served through /validate) through the live "
                   "policy epoch on the micro-batcher's best-effort lane "
                   "— live traffic strictly preempts audit work. "
                   "'interval' sweeps the dirty set on a cadence and "
                   "fully on every policy-epoch promotion; 'on-promote' "
                   "sweeps fully on epoch flips only; 'off' disables the "
                   "scanner and the GET /audit/reports endpoints")),
        ("--audit-interval-seconds", "KUBEWARDEN_AUDIT_INTERVAL_SECONDS",
         dict(type=float, default=30.0, metavar="SECONDS",
              help="Dirty-set sweep cadence for --audit-mode interval "
                   "(objects served through /validate since the last "
                   "sweep are re-judged)")),
        ("--audit-batch-size", "KUBEWARDEN_AUDIT_BATCH_SIZE",
         dict(type=int, default=256, metavar="N",
              help="Rows per best-effort audit-lane batch (audit rides "
                   "idle device slots in large batches; at most one "
                   "audit dispatch is ever in flight)")),
        ("--audit-max-snapshot-bytes", "KUBEWARDEN_AUDIT_MAX_SNAPSHOT_BYTES",
         dict(default="64Mi", metavar="BYTES",
              help="Byte budget of the audit snapshot store holding "
                   "cluster resources as pre-encoded admission rows "
                   "(accepts K/M/G[i] suffixes; least-recently-recorded "
                   "rows evict beyond it)")),
        ("--audit-resources-file", "KUBEWARDEN_AUDIT_RESOURCES_FILE",
         dict(default=None, metavar="RESOURCES_FILE",
              help="YAML/JSON file of Kubernetes objects (a list or a "
                   "List document) seeding the audit snapshot store at "
                   "boot — the stand-in for the companion audit "
                   "scanner's cluster LIST")),
        ("--audit-watch", "KUBEWARDEN_AUDIT_WATCH",
         dict(action="store_true",
              help="Feed the audit snapshot store from the Kubernetes "
                   "list+watch stream (audit/watch_feed.py): "
                   "ADDED/MODIFIED events supersede, DELETED evicts and "
                   "prunes report rows, and the scanner then audits the "
                   "LIVE cluster instead of only /validate traffic and "
                   "the seed file. Streams resume from the last "
                   "resourceVersion on clean close; faults and "
                   "queue overflows force a counted full re-LIST "
                   "resync. Requires --audit-mode interval|on-promote")),
        ("--audit-watch-resources", "KUBEWARDEN_AUDIT_WATCH_RESOURCES",
         dict(default="v1/Pod,v1/Namespace,apps/v1/Deployment,"
                      "apps/v1/ReplicaSet,apps/v1/StatefulSet,"
                      "apps/v1/DaemonSet",
              metavar="KINDS",
              help="Comma-separated apiVersion/Kind list the audit "
                   "watch feed follows (e.g. 'v1/Pod,apps/v1/"
                   "Deployment')")),
        ("--audit-watch-max-queue-events",
         "KUBEWARDEN_AUDIT_WATCH_MAX_QUEUE_EVENTS",
         dict(type=int, default=65536, metavar="N",
              help="Bound of the watch-event queue between the per-kind "
                   "watcher threads and the snapshot applier; an "
                   "overflow drops the event (counted loudly) and "
                   "forces a full re-LIST resync of that kind, so a "
                   "drop can delay freshness but never corrupt the "
                   "inventory")),
        ("--audit-matrix", "KUBEWARDEN_AUDIT_MATRIX",
         dict(action="store_true",
              help="Maintain the persistent (object × policy) verdict "
                   "matrix (audit/matrix.py): sweeps evaluate only the "
                   "dirty cross-product (dirty rows × all columns + "
                   "clean rows × dirty columns — a promotion changing 2 "
                   "of 32 policies re-judges 2 columns, not the "
                   "cluster), verdict changes stream on GET "
                   "/audit/stream with a monotonic matrixVersion "
                   "cursor, columns spill through --state-dir for warm "
                   "resume, and a /validate UPDATE byte-identical to a "
                   "judged row answers from the precomputed verdict. "
                   "Requires --audit-mode interval|on-promote")),
        ("--audit-stream-max-clients",
         "KUBEWARDEN_AUDIT_STREAM_MAX_CLIENTS",
         dict(type=int, default=64, metavar="N",
              help="Cap on concurrent GET /audit/stream clients; beyond "
                   "it new subscribers get an in-band 503 (each client "
                   "holds a bounded changelog queue — a slow consumer "
                   "overflows its own queue and is dropped with a "
                   "counted close, never blocking the applier)")),
        ("--audit-matrix-spill-seconds",
         "KUBEWARDEN_AUDIT_MATRIX_SPILL_SECONDS",
         dict(type=float, default=30.0, metavar="SECONDS",
              help="Verdict-matrix spill cadence: how often the scanner "
                   "spills the matrix columns (epoch-fingerprint-keyed) "
                   "to --state-dir so a warm restart resumes compliance "
                   "without re-judging clean rows")),
        ("--audit-matrix-whatif", "KUBEWARDEN_AUDIT_MATRIX_WHATIF",
         dict(action="store_true",
              help="During a reload's shadow canary, also evaluate the "
                   "CANDIDATE epoch's changed columns against the live "
                   "audit snapshot and surface the cluster-wide what-if "
                   "verdict diff on the reload status — canarying over "
                   "the whole cluster, not just the request ring. "
                   "Requires --audit-matrix")),
        ("--native-idle-timeout-seconds",
         "KUBEWARDEN_NATIVE_IDLE_TIMEOUT_SECONDS",
         dict(type=float, default=75.0, metavar="SECONDS",
              help="Native frontend: close keep-alive connections idle "
                   "longer than this between requests (aiohttp "
                   "keepalive parity; 0 disables)")),
        ("--native-read-timeout-seconds",
         "KUBEWARDEN_NATIVE_READ_TIMEOUT_SECONDS",
         dict(type=float, default=30.0, metavar="SECONDS",
              help="Native frontend: a single request (header+body) "
                   "must ARRIVE in full within this bound or the "
                   "connection is closed — the slowloris defense "
                   "(drips refresh byte activity but never complete "
                   "the request; 0 disables)")),
        ("--native-max-connections", "KUBEWARDEN_NATIVE_MAX_CONNECTIONS",
         dict(type=int, default=0, metavar="N",
              help="Native frontend: cap on concurrent connections; "
                   "accepts over it answer an in-band 503 + "
                   "Retry-After and close (counted; 0 = uncapped)")),
        ("--native-tls", "KUBEWARDEN_NATIVE_TLS",
         dict(default="auto", metavar="MODE", choices=["auto", "off"],
              help="Native frontend TLS termination: 'auto' terminates "
                   "TLS on the C++ epoll loops when --cert/--key are "
                   "set and libssl loads — SIGHUP/digest hot-rotation "
                   "atomically swaps the SSL_CTX for NEW connections "
                   "while established ones drain on the old identity, "
                   "and a failed reload keeps last-good serving; when "
                   "libssl is missing the server falls back LOUDLY to "
                   "the aiohttp TLS frontend. 'off' keeps aiohttp "
                   "terminating TLS even under --frontend native")),
        ("--native-tls-handshake-timeout-seconds",
         "KUBEWARDEN_NATIVE_TLS_HANDSHAKE_TIMEOUT_SECONDS",
         dict(type=float, default=10.0, metavar="SECONDS",
              help="Native TLS: the full handshake must COMPLETE "
                   "within this window measured from accept — byte "
                   "drips never refresh it, so a TLS-layer slowloris "
                   "is reaped on schedule (0 disables)")),
        ("--tenants", "KUBEWARDEN_TENANTS",
         dict(default=None, metavar="TENANTS_FILE",
              help="Multi-tenant serving (round 16, tenancy.py): a YAML "
                   "manifest mapping tenant names to their own policies "
                   "files plus per-tenant knobs — weight (weighted-fair "
                   "dispatch share), quota-rows-per-second + quota-burst "
                   "(token-bucket admission; overflow answers 429 + "
                   "Retry-After), max-inflight (admitted-unresolved row "
                   "cap), request-timeout-ms (per-tenant deadline "
                   "class), and degraded-mode (per-tenant breaker "
                   "fallback). Each named tenant owns an independent "
                   "epoch lifecycle (reload/canary/rollback/digest "
                   "watch) over its policies file and is served at "
                   "POST /validate/{tenant}/{policy_id} (plus the "
                   "audit/raw variants and GET /readiness/{tenant}); "
                   "every un-prefixed URL stays the reserved 'default' "
                   "tenant, configured by --policies as before. A "
                   "top-level 'default:' entry applies quota/weight "
                   "knobs to the default tenant; "
                   "'max-concurrent-dispatches' caps the shared "
                   "weighted-fair dispatch scheduler. Unset = "
                   "single-tenant, bit-identical to the pre-tenancy "
                   "serving path")),
        ("--reload-admin-token", "KUBEWARDEN_RELOAD_ADMIN_TOKEN",
         dict(default=None, metavar="TOKEN",
              help="Bearer token authenticating the policy-lifecycle "
                   "admin endpoints (POST /policies/reload, /policies/"
                   "promote, /policies/rollback on the readiness port); "
                   "unset disables them")),
        ("--state-dir", "KUBEWARDEN_STATE_DIR",
         dict(default=None, metavar="DIR",
              help="Durable last-good state directory (round 17, "
                   "statestore.py): a crash-consistent store (atomic "
                   "tmp+fsync+rename writes, CRC-framed generation-"
                   "numbered journals) holding (a) a content-addressed "
                   "policy artifact cache shared by boot and hot-reload "
                   "fetch, (b) per-tenant last-good epoch manifests "
                   "persisted on every promotion/rollback so the "
                   "rollback pin survives restarts, and (c) the audit "
                   "snapshot spill (resourceVersion cursors + "
                   "inventory) so the watch feed RESUMES instead of "
                   "re-LISTing the cluster. A warm boot whose policies "
                   "config matches the last-good manifest loads pinned "
                   "artifacts from the cache with ZERO network fetches; "
                   "a failed fetch degrades loudly to last-good instead "
                   "of fail-closing. Corrupt or torn entries are "
                   "quarantined by the boot fsck pass, never fatal. "
                   "Pair with --compilation-cache-dir inside it so "
                   "compiled programs survive too. Unset = amnesiac "
                   "restarts (every boot refetches and re-LISTs)")),
        ("--state-audit-spill-seconds", "KUBEWARDEN_STATE_AUDIT_SPILL_SECONDS",
         dict(type=float, default=30.0, metavar="SECONDS",
              help="Cadence of the audit snapshot spill into the state "
                   "dir (one atomic journal replace per tick; also "
                   "spilled on clean shutdown). Only with --state-dir "
                   "and --audit-watch")),
        ("--flight-recorder", "KUBEWARDEN_FLIGHT_RECORDER",
         dict(default="on", metavar="MODE", choices=["on", "off"],
              help="Always-on flight recorder (round 18, telemetry/"
                   "flightrec.py): a lock-free per-process ring of "
                   "nanosecond-stamped phase events covering the full "
                   "request lifecycle — native accept/parse/ring-cross "
                   "(stamped in the C++ frontend and carried across the "
                   "SPSC ring), batcher admission/queue-wait/formation, "
                   "encode, dispatch, device execute, fetch, deliver, "
                   "native verdict serialize — at <2% overhead (one "
                   "clock read per phase boundary per BATCH; per-row "
                   "events only on sampled rows). Read surfaces: GET "
                   "/debug/timeline (Chrome/Perfetto trace JSON, on the "
                   "readiness port and the python-frontend API port), "
                   "per-phase latency histograms + tail exemplars on "
                   "/metrics and OTLP, and the phase-attribution report "
                   "(make phase-report). 'off' disables the recorder "
                   "and the timeline endpoint")),
        ("--recorder-ring-events", "KUBEWARDEN_RECORDER_RING_EVENTS",
         dict(type=int, default=65536, metavar="N",
              help="Flight-recorder ring capacity in events (rounded up "
                   "to a power of two; ~10 batch events per dispatched "
                   "batch, so the default holds the last ~6.5k batches; "
                   "older events are overwritten, never blocked on)")),
        ("--recorder-row-sample-rate", "KUBEWARDEN_RECORDER_ROW_SAMPLE_RATE",
         dict(type=float, default=0.01, metavar="FRACTION",
              help="Fraction of delivered rows that record per-row "
                   "timeline segments on the flight recorder "
                   "(deterministic 1-in-round(1/FRACTION) stride — no "
                   "RNG on the serving path; 0 disables row sampling "
                   "while batch events and tail exemplars remain)")),
        ("--selfheal-interval-seconds", "KUBEWARDEN_SELFHEAL_INTERVAL_SECONDS",
         dict(type=float, default=5.0, metavar="SECONDS",
              help="Main-process self-heal watchdog cadence "
                   "(supervision.py): every tick it verifies the "
                   "batcher dispatch loops (every tenant's) and the "
                   "native frontend's drainer thread are alive, and "
                   "REBUILDS a wedged one instead of serving zombies "
                   "(counted on /metrics as "
                   "policy_server_selfheal_*_revives). 0 disables")),
        ("--serving-shards", "KUBEWARDEN_SERVING_SHARDS",
         dict(type=int, default=1, metavar="M",
              help="Host-local serving shards (runtime/shards.py): M "
                   "full serving stacks — each with its own evaluation "
                   "environment (verdict cache + breaker) and "
                   "micro-batcher, sharing the promoted epoch artifacts "
                   "and the XLA compilation cache read-only — behind a "
                   "health + queue-depth-EWMA router. A shard whose "
                   "dispatch loop wedges or dies is fenced within one "
                   "heartbeat interval (queued rows re-routed to a "
                   "sibling or answered 503 with Retry-After, never "
                   "double-answered) and warm-revived in place without "
                   "touching its siblings; SIGTERM drains shards in "
                   "sequence. 1 bypasses the router entirely — the "
                   "serving path is byte-identical to a routerless "
                   "build")),
        ("--shard-heartbeat-seconds", "KUBEWARDEN_SHARD_HEARTBEAT_SECONDS",
         dict(type=float, default=0.5, metavar="SECONDS",
              help="Shard router heartbeat cadence: each tick probes "
                   "every shard's dispatch loop, fences a wedged/dead "
                   "shard (draining its queued rows to the healthiest "
                   "sibling), and warm-revives it. Bounds the fencing "
                   "latency after a shard death. Ignored when "
                   "--serving-shards is 1")),
        ("--worker-respawn-giveup", "KUBEWARDEN_WORKER_RESPAWN_GIVEUP",
         dict(type=int, default=5, metavar="N",
              help="Prefork respawn breaker: a frontend worker slot "
                   "that crash-loops N consecutive times within the "
                   "crash window stops respawning (exponential backoff "
                   "applies before the cap); the remaining processes "
                   "keep serving and /readiness reports the degraded "
                   "slot honestly")),
        ("--mesh", "KUBEWARDEN_MESH",
         dict(default="auto", metavar="MESH_SPEC",
              help="Device mesh spec, e.g. 'auto', 'data:8', 'data:4,policy:2'")),
        ("--mesh-dispatch", "KUBEWARDEN_MESH_DISPATCH",
         dict(default="fused", metavar="MODE", choices=["fused", "threaded"],
              help="How a >1 policy axis executes (round 14): 'fused' "
                   "lowers the whole policy set as ONE SPMD program over "
                   "the (data x policy) mesh — each policy shard is a "
                   "lax.switch branch selected by its mesh position, "
                   "verdict blocks meet in an all-gather collective, and "
                   "XLA overlaps the cross-shard work (one device program "
                   "per batch); 'threaded' keeps the legacy "
                   "thread-per-shard MPMD dispatcher (one program per "
                   "policy shard, host-side thread joins) as a fallback")),
        ("--no-warmup", "KUBEWARDEN_NO_WARMUP",
         dict(action="store_true",
              help="Skip AOT compilation of the policy program at boot")),
        ("--compilation-cache-dir", "KUBEWARDEN_COMPILATION_CACHE_DIR",
         dict(default=None, metavar="DIR",
              help="Persistent XLA compilation cache directory: compiled "
                   "policy programs survive restarts (the TPU analog of the "
                   "reference's policies-download store reuse)")),
        ("--http-workers", "KUBEWARDEN_HTTP_WORKERS",
         dict(type=int, default=1, metavar="N",
              help="HTTP frontend processes sharing the API port via "
                   "SO_REUSEPORT, forwarding to the evaluation process "
                   "over a unix socket (1 = serve in-process; raises the "
                   "~1.3k req/s per-event-loop framing ceiling, see "
                   "PROFILE.md)")),
        ("--frontend", "KUBEWARDEN_FRONTEND",
         dict(default="python", metavar="IMPL", choices=["python", "native"],
              help="HTTP framing implementation for the evaluation POST "
                   "surface (/validate, /validate_raw, /audit): 'native' "
                   "serves it from the GIL-free C++ epoll front-end "
                   "(csrc/httpfront.cpp) that parses AdmissionReviews "
                   "straight into packed batch rows and serializes "
                   "verdicts natively — breaking the ~1.3k rps/process "
                   "Python framing ceiling (PROFILE.md); 'python' keeps "
                   "aiohttp framing, the always-available fallback and "
                   "differential correctness oracle. With 'native', the "
                   "API port serves ONLY the evaluation POSTs — "
                   "/audit/reports, /metrics, and the /policies/* admin "
                   "surface stay on the readiness port, and the pprof "
                   "endpoints require --frontend python; a native build "
                   "that fails to load falls back to 'python' with a "
                   "loud warning. Under --http-workers, the "
                   "policy_server_native_* /metrics families count the "
                   "main process's loop only (worker processes export "
                   "no metrics, matching the python prefork mode)")),
        ("--context-refresh-seconds", "KUBEWARDEN_CONTEXT_REFRESH_SECONDS",
         dict(type=float, default=30.0, metavar="SECONDS",
              help="Context-aware snapshot freshness: the re-LIST period in "
                   "poll mode; in watch mode (snapshots are event-fresh) "
                   "the error-backoff cap, with a full re-LIST resync every "
                   "10x this value (staleness contract: context/service.py)")),
        ("--context-no-watch", "KUBEWARDEN_CONTEXT_NO_WATCH",
         dict(action="store_true",
              help="Disable the Kubernetes watch stream for context-aware "
                   "snapshots and poll with periodic LISTs instead")),
        ("--distributed-coordinator", "KUBEWARDEN_DISTRIBUTED_COORDINATOR",
         dict(default=None, metavar="HOST:PORT",
              help="jax.distributed coordinator address for multi-host "
                   "serving; when set, bootstrap initializes the DCN "
                   "process group before building the device mesh "
                   "(SURVEY.md §7.2 step 10)")),
        ("--distributed-num-processes", "KUBEWARDEN_DISTRIBUTED_NUM_PROCESSES",
         dict(type=int, default=None, metavar="N",
              help="Total number of policy-server processes in the "
                   "multi-host group (requires --distributed-coordinator)")),
        ("--distributed-process-id", "KUBEWARDEN_DISTRIBUTED_PROCESS_ID",
         dict(type=int, default=None, metavar="ID",
              help="This process's rank in the multi-host group "
                   "(requires --distributed-coordinator)")),
    ]


def long_version() -> str:
    """``--long-version`` banner: version + the predicate-IR op registry +
    the OPA builtins host registry (reference prints the burrego builtins,
    cli.rs:7-21)."""
    from policy_server_tpu.ops.ir import registered_op_names
    from policy_server_tpu.wasm.builtins import get_builtins

    ops = "\n".join(f"  - {name}" for name in registered_op_names())
    builtins = "\n".join(f"  - {name}" for name in sorted(get_builtins()))
    return (
        f"{PROG} {__version__}\npredicate IR ops:\n{ops}\n\n"
        f"Open Policy Agent/Gatekeeper implemented builtins:\n{builtins}"
    )


def build_cli() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog=PROG,
        description=(
            "TPU-native Kubernetes admission policy server: micro-batched "
            "JAX/XLA policy evaluation with the capability surface of "
            "Kubewarden's policy-server."
        ),
    )
    parser.add_argument("--version", action="version", version=f"{PROG} {__version__}")
    parser.add_argument(
        "--long-version",
        action="store_true",
        help="Print version information and the predicate-IR op registry",
    )
    for flag, env, kwargs in _flag_specs():
        kwargs = dict(kwargs)
        if env is not None:
            if kwargs.get("action") == "store_true":
                kwargs["default"] = _env_flag(env)
            elif kwargs.get("action") == "append":
                env_val = _env(env)
                if env_val is not None:
                    kwargs["default"] = env_val.split(",")
            else:
                env_val = _env(env)
                if env_val is not None:
                    t = kwargs.get("type", str)
                    kwargs["default"] = t(env_val)
            if kwargs.get("help") and kwargs["help"] is not argparse.SUPPRESS:
                kwargs["help"] += f" [env: {env}]"
        parser.add_argument(flag, **kwargs)

    sub = parser.add_subparsers(dest="subcommand")
    docs = sub.add_parser(
        "docs", help="Generates the markdown documentation for the CLI"
    )
    docs.add_argument(
        "--output", "-o", required=True, metavar="FILE", help="path where to save the docs file"
    )
    return parser


def generate_docs() -> str:
    """Render the markdown CLI reference (reference cli-docs.md generated by
    the ``docs`` subcommand, main.rs:68)."""
    lines = [
        f"# Command-Line Help for `{PROG}`",
        "",
        f"This document contains the help content for the `{PROG}` command-line program.",
        "",
        f"## `{PROG}`",
        "",
        f"**Usage:** `{PROG} [OPTIONS] [COMMAND]`",
        "",
        "###### **Subcommands:**",
        "",
        "* `docs` — Generates the markdown documentation for the CLI",
        "",
        "###### **Options:**",
        "",
    ]
    for flag, env, kwargs in _flag_specs():
        help_text = kwargs.get("help")
        if help_text is argparse.SUPPRESS:
            continue
        metavar = kwargs.get("metavar")
        action = kwargs.get("action")
        head = flag if action in ("store_true",) else f"{flag} <{metavar}>"
        lines.append(f"* `{head}` — {help_text}")
        if env:
            lines.append(f"  [env: `{env}`]")
        default = kwargs.get("default")
        if default not in (None, False, []):
            lines.append("")
            lines.append(f"  Default value: `{default}`")
        choices = kwargs.get("choices")
        if choices:
            lines.append("")
            lines.append("  Possible values: " + ", ".join(f"`{c}`" for c in choices))
        lines.append("")
    return "\n".join(lines)


def main(argv: Sequence[str] | None = None) -> int:
    """Process entry (reference src/main.rs:15-65)."""
    parser = build_cli()
    args = parser.parse_args(argv)

    if args.long_version:
        print(long_version())
        return 0

    if args.subcommand == "docs":
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(generate_docs())
        return 0

    from policy_server_tpu.server import run_server

    return run_server(args)


if __name__ == "__main__":
    sys.exit(main())
