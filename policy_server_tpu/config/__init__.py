"""Config layer: CLI parsing, policies.yml / sources.yml / verification.yml.

Reference parity: src/cli.rs + src/config.rs.
"""

from policy_server_tpu.config.config import Config, TlsConfig
from policy_server_tpu.config.sources import Sources, read_sources_file
from policy_server_tpu.config.verification import VerificationConfig, read_verification_file

__all__ = [
    "Config",
    "TlsConfig",
    "Sources",
    "read_sources_file",
    "VerificationConfig",
    "read_verification_file",
]
