"""verification.yml: sigstore verification requirements for policy artifacts.

Reference parity: policy-fetcher's ``LatestVerificationConfig`` /
``VerificationConfigV1`` as used at src/config.rs (read_verification_file)
and verification.yml.example — ``allOf`` (every signature requirement must
match) and ``anyOf`` with ``minimumMatches`` (default 1). Signature
requirement kinds: ``pubKey``, ``genericIssuer`` (subject equal/urlPrefix),
``githubAction``.

Full keyless (Fulcio/Rekor TUF) verification requires network egress; this
module models and validates the config schema, and fetch/verify.py applies
the subset that is verifiable hermetically (pubKey signatures, digest
checksums). Unsupported kinds are reported, not silently accepted.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Mapping

import yaml

_SIGNATURE_KINDS = {"pubKey", "genericIssuer", "githubAction"}


@dataclass(frozen=True)
class Subject:
    """genericIssuer subject matcher: exactly one of equal / urlPrefix.

    urlPrefix is post-fixed with '/' when not already present
    (verification.yml.example note: "for security reasons")."""

    equal: str | None = None
    url_prefix: str | None = None

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "Subject":
        equal = d.get("equal")
        prefix = d.get("urlPrefix")
        if (equal is None) == (prefix is None):
            raise ValueError("subject requires exactly one of `equal` / `urlPrefix`")
        if prefix is not None and not prefix.endswith("/"):
            prefix = prefix + "/"
        return cls(equal=equal, url_prefix=prefix)

    def matches(self, subject: str) -> bool:
        if self.equal is not None:
            return subject == self.equal
        assert self.url_prefix is not None
        return subject.startswith(self.url_prefix)


@dataclass(frozen=True)
class SignatureRequirement:
    kind: str
    owner: str | None = None
    repo: str | None = None
    key: str | None = None
    issuer: str | None = None
    subject: Subject | None = None
    annotations: Mapping[str, str] | None = None

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "SignatureRequirement":
        kind = d.get("kind")
        if kind not in _SIGNATURE_KINDS:
            raise ValueError(
                f"unknown signature kind {kind!r}; expected one of {sorted(_SIGNATURE_KINDS)}"
            )
        if kind == "pubKey" and not d.get("key"):
            raise ValueError("pubKey signature requires `key`")
        if kind == "genericIssuer":
            if not d.get("issuer"):
                raise ValueError("genericIssuer signature requires `issuer`")
            if not isinstance(d.get("subject"), Mapping):
                raise ValueError("genericIssuer signature requires `subject`")
        if kind == "githubAction" and not d.get("owner"):
            raise ValueError("githubAction signature requires `owner`")
        annotations = d.get("annotations")
        return cls(
            kind=kind,
            owner=d.get("owner"),
            repo=d.get("repo"),
            key=d.get("key"),
            issuer=d.get("issuer"),
            subject=Subject.from_dict(d["subject"]) if kind == "genericIssuer" else None,
            annotations=dict(annotations) if isinstance(annotations, Mapping) else None,
        )


@dataclass
class AnyOf:
    minimum_matches: int = 1
    signatures: tuple[SignatureRequirement, ...] = ()

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "AnyOf":
        minimum = d.get("minimumMatches", 1)
        if not isinstance(minimum, int) or minimum < 1:
            raise ValueError("anyOf.minimumMatches must be a positive integer")
        sigs = tuple(
            SignatureRequirement.from_dict(s) for s in (d.get("signatures") or [])
        )
        if len(sigs) < minimum:
            raise ValueError(
                "anyOf has fewer signatures than minimumMatches "
                f"({len(sigs)} < {minimum})"
            )
        return cls(minimum_matches=minimum, signatures=sigs)


@dataclass
class VerificationConfig:
    """apiVersion v1 verification config."""

    api_version: str = "v1"
    all_of: tuple[SignatureRequirement, ...] = ()
    any_of: AnyOf | None = None

    @classmethod
    def from_dict(cls, doc: Mapping[str, Any]) -> "VerificationConfig":
        if not isinstance(doc, Mapping):
            raise ValueError("verification file must contain a mapping")
        api_version = doc.get("apiVersion")
        if api_version != "v1":
            raise ValueError(f"unsupported verification config apiVersion: {api_version!r}")
        all_of = tuple(
            SignatureRequirement.from_dict(s) for s in (doc.get("allOf") or [])
        )
        any_of_doc = doc.get("anyOf")
        any_of = AnyOf.from_dict(any_of_doc) if any_of_doc is not None else None
        if not all_of and any_of is None:
            raise ValueError("verification config must define allOf and/or anyOf")
        return cls(api_version="v1", all_of=all_of, any_of=any_of)


def read_verification_file(path: str | Path) -> VerificationConfig:
    with open(path, "r", encoding="utf-8") as f:
        doc = yaml.safe_load(f)
    return VerificationConfig.from_dict(doc)
