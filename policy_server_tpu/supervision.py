"""Supervised recovery — respawn accounting and the self-heal watchdog.

Two in-process supervision surfaces ride here (round 17, the process-
level complement of statestore.py's durable state):

* :class:`SupervisorStats` — the locked counter block behind the
  ``policy_server_worker_respawn*`` / ``policy_server_selfheal_*``
  /metrics families. The prefork worker supervisor (server.py
  ``_supervise_workers``) feeds the respawn/backoff/give-up counters;
  the watchdog below feeds the revive counters.

* :class:`SelfHealWatchdog` — a daemon thread that periodically verifies
  the serving threads a request actually depends on are ALIVE: every
  tenant batcher's dispatch loop and the native frontend's drainer. A
  thread that died outside shutdown is a zombie server — the port stays
  bound, readiness keeps answering 200, and every request times out.
  The watchdog REBUILDS the dead thread (``MicroBatcher.
  revive_dispatch`` / ``NativeFrontend.revive_drainer``), counts the
  revive loudly, and serving resumes — the in-box analog of kubelet
  restarting a wedged container, without dropping the process's warm
  state.
"""

from __future__ import annotations

import threading
from typing import Any

from policy_server_tpu.telemetry.tracing import logger


class SupervisorStats:
    """Locked counters for the supervision /metrics families (scraped
    through ``runtime_stats`` via ``ApiServerState.supervisor``)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._worker_respawns = 0  # guarded-by: _lock
        self._worker_backoff_seconds = 0.0  # guarded-by: _lock
        self._worker_slots_given_up = 0  # guarded-by: _lock
        self._batcher_revives = 0  # guarded-by: _lock
        self._frontend_revives = 0  # guarded-by: _lock

    def count_respawn(self, backoff_seconds: float = 0.0) -> None:
        with self._lock:
            self._worker_respawns += 1
            self._worker_backoff_seconds += max(0.0, backoff_seconds)

    def count_slot_given_up(self) -> None:
        with self._lock:
            self._worker_slots_given_up += 1

    def count_batcher_revive(self) -> None:
        with self._lock:
            self._batcher_revives += 1

    def count_frontend_revive(self) -> None:
        with self._lock:
            self._frontend_revives += 1

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "worker_respawns": self._worker_respawns,
                "worker_backoff_seconds": self._worker_backoff_seconds,
                "worker_slots_given_up": self._worker_slots_given_up,
                "batcher_revives": self._batcher_revives,
                "frontend_revives": self._frontend_revives,
            }


class SelfHealWatchdog:
    """Periodic liveness check + rebuild of the serving threads (see
    module docstring). ``state`` is the ApiServerState — the watchdog
    reads batchers THROUGH it so it follows epoch flips and covers every
    tenant."""

    def __init__(
        self,
        state: Any,
        stats: SupervisorStats,
        interval_seconds: float = 5.0,
    ) -> None:
        self.state = state
        self.stats = stats
        self.interval_seconds = float(interval_seconds)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "SelfHealWatchdog":
        if self.interval_seconds <= 0 or self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, name="selfheal-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _batchers(self) -> list[Any]:
        out = [self.state.batcher]
        tenants = self.state.tenants
        if tenants is not None:
            try:
                for t in tenants.all():
                    b = getattr(t.state, "batcher", None)
                    if b is not None and b not in out:
                        out.append(b)
            except Exception:  # noqa: BLE001 — introspection best-effort
                pass
        return out

    def check_once(self) -> int:
        """One liveness pass; returns the number of revives performed
        (exposed for tests and for a manual poke)."""
        revived = 0
        for batcher in self._batchers():
            try:
                if batcher.dispatch_wedged() and batcher.revive_dispatch():
                    self.stats.count_batcher_revive()
                    revived += 1
                    logger.error(
                        "self-heal: batcher dispatch loop was DEAD "
                        "outside shutdown — rebuilt it (queue depth %d); "
                        "a zombie batcher would have timed out every "
                        "request while readiness kept answering 200",
                        batcher.queue_depth(),
                    )
            except Exception as e:  # noqa: BLE001 — the watchdog must
                logger.error("self-heal batcher check failed: %s", e)
        front = self.state.native_frontend
        if front is not None:
            try:
                if front.drainer_wedged() and front.revive_drainer():
                    self.stats.count_frontend_revive()
                    revived += 1
                    logger.error(
                        "self-heal: native frontend drainer was DEAD "
                        "outside shutdown — rebuilt it; parsed requests "
                        "would otherwise rot in the submission rings"
                    )
            except Exception as e:  # noqa: BLE001
                logger.error("self-heal frontend check failed: %s", e)
        return revived

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_seconds):
            self.check_once()
