"""policy_server_tpu — a TPU-native Kubernetes admission policy framework.

A brand-new framework with the capability surface of Kubewarden's
policy-server (reference: /root/reference, v1.23.0): an HTTPS admission
controller that loads policies from OCI registries/HTTP/file, validates
AdmissionReview documents against them (single policies and boolean policy
groups), enforces monitor/protect modes and mutation gating, and exports
OTLP traces/metrics — but re-architected TPU-first:

* Policies are expressed in a tensorizable predicate IR (see
  ``policy_server_tpu.ops.ir``) instead of per-request WASM instantiation
  (reference: src/evaluation/evaluation_environment.rs).
* Incoming AdmissionReviews are flattened into fixed-shape feature tensors
  by a policy-derived codec (``ops.codec``) and evaluated micro-batched as
  one fused, jit-compiled predicate program per batch
  (``evaluation.environment``, ``parallel.batcher``).
* Scale-out is a ``jax.sharding.Mesh`` with data- and policy-axis sharding
  (``parallel.mesh``), not an HTTP load balancer.
* A host-side interpreter of the same IR (``evaluation.oracle``) is the
  bit-exact correctness oracle standing in for the reference's wasmtime
  path.
"""

from policy_server_tpu.version import __version__

__all__ = ["__version__"]
