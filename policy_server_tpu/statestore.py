"""Durable last-good state store — the crash-tolerance substrate.

The reference admission controller's worst failure mode is the boot
path: the server refetches every policy from OCI registries on every
start, so a restart during a registry outage is a total outage, and a
crash forgets everything the process learned — the last-good epoch pin,
the compiled-and-validated policy artifacts, the audit feed's
resourceVersion cursor. Rounds 7-16 built deep *in-flight* resilience
(breakers, shedding, canary reload, tenant isolation); this module makes
the PROCESS itself restartable: a crash becomes a bounded, measured
event instead of a cold start.

``--state-dir`` points at one directory holding three sections:

* **Content-addressed artifact cache** (``artifacts/<sha256>``): the raw
  bytes of every policy module the fetch subsystem ever resolved, keyed
  by digest, with a journaled url→digest map. Boot and hot-reload share
  it through the module resolver: when the current policies config is
  byte-identical to the last-good manifest, pinned artifacts load
  straight from the cache (zero network — the registry can be DOWN);
  when the config changed, live fetch is preferred and the cache is the
  loud last-good fallback on fetch failure.
* **Per-tenant last-good epoch manifests** (``manifests.journal``):
  persisted on every promotion, rollback, and boot — the policies.yml
  digest AND raw bytes, the artifact digests the epoch resolved, and the
  schema/optimizer fingerprint keyed to the persistent XLA compile cache
  — so the rollback pin survives restarts and a warm boot can prove its
  compile-cache validity.
* **Audit snapshot spill** (``audit/spill.journal``): the watch feed's
  per-kind resourceVersion cursors plus the snapshot store's pre-encoded
  inventory, spilled periodically — a restart resumes the watch streams
  instead of re-LISTing a 100k-object cluster.

Crash-consistency contract: EVERY write under the state dir goes through
:func:`atomic_write_bytes` (tmp + fsync + rename + directory fsync —
graftcheck rule FS01 enforces this statically), and journal files are
sequences of CRC-framed, generation-numbered records, so any observable
on-disk state is a complete, internally-consistent generation. Torn or
bit-flipped state never crashes the boot: the :meth:`StateStore.fsck`
pass (run at open) quarantines anything that fails framing, CRC, or
content-address verification into ``quarantine/`` and salvages the valid
record prefix — boot then lands on the newest VALID generation (or clean
cold when nothing survives), never on a silently wrong epoch.
"""

from __future__ import annotations

import binascii
import hashlib
import itertools
import json
import os
import struct
import threading
import time
from pathlib import Path
from typing import Any, Iterable, Mapping

from policy_server_tpu.telemetry.tracing import logger

# journal record framing: magic | generation (u64) | payload length (u32)
# | crc32 of payload (u32) | payload (JSON). Big-endian so a hex dump is
# human-checkable during an incident.
_MAGIC = b"TPSJ"
_HEADER = struct.Struct(">4sQII")

# retention: how many manifest generations each tenant keeps in the
# journal (current + the pinned previous — the on-disk analog of the
# lifecycle's one-generation rollback pin window)
_MANIFEST_RETENTION = 2


_tmp_counter = itertools.count()


def atomic_write_bytes(path: str | Path, data: bytes) -> None:  # graftcheck: fs-atomic
    """The ONE durable write primitive for the state dir: write to a
    same-directory temp file, flush + fsync it, atomically rename over
    the destination, then fsync the directory so the rename itself is
    durable. A crash at ANY point leaves either the old complete file or
    the new complete file — never a torn mix (graftcheck FS01 lints that
    no other write path touches the state dir). The temp name carries a
    process-wide counter on top of the pid: concurrent same-process
    writers (N tenants promoting on one SIGHUP) must never share a temp
    file."""
    path = Path(path)
    tmp = path.with_name(
        f"{path.name}.tmp.{os.getpid()}.{next(_tmp_counter)}"
    )
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    dir_fd = os.open(str(path.parent), os.O_RDONLY)
    try:
        os.fsync(dir_fd)
    finally:
        os.close(dir_fd)


def _record_crc(gen: int, body: bytes) -> int:
    # the CRC covers the generation too: a bit-flipped header must not
    # reorder otherwise-valid records
    return binascii.crc32(body, binascii.crc32(struct.pack(">Q", gen))) \
        & 0xFFFFFFFF


def frame_records(records: Iterable[tuple[int, dict]]) -> bytes:
    """Serialize (generation, payload-dict) pairs into journal bytes."""
    out = bytearray()
    for gen, payload in records:
        body = json.dumps(payload, separators=(",", ":")).encode()
        out += _HEADER.pack(
            _MAGIC, int(gen), len(body), _record_crc(gen, body)
        )
        out += body
    return bytes(out)


def parse_records(data: bytes) -> tuple[list[tuple[int, dict]], bool]:
    """Parse journal bytes → ``(records, corrupt)``. Reading stops at the
    first framing/CRC/JSON failure — once one record is untrustworthy,
    so is every length-prefixed byte after it — and ``corrupt`` is True
    when ANY trailing bytes were discarded. The valid prefix is always
    returned: a torn tail costs at most the newest generation, never the
    journal."""
    records: list[tuple[int, dict]] = []
    off = 0
    n = len(data)
    while off < n:
        if off + _HEADER.size > n:
            return records, True  # torn header
        magic, gen, length, crc = _HEADER.unpack_from(data, off)
        if magic != _MAGIC or length > n - off - _HEADER.size:
            return records, True
        body = data[off + _HEADER.size: off + _HEADER.size + length]
        if _record_crc(gen, body) != crc:
            return records, True
        try:
            payload = json.loads(body)
        except ValueError:
            return records, True
        if not isinstance(payload, dict):
            return records, True
        records.append((gen, payload))
        off += _HEADER.size + length
    return records, False


def compute_fingerprint(parts: Mapping[str, Any]) -> str:
    """Schema/optimizer fingerprint: a digest over everything that keys
    the persistent XLA compile cache's validity for this policy set —
    the policy ids, the lowering knobs (optimizer/kernel/columnar/
    backend), and the jax version. A warm boot whose fingerprint matches
    the last-good manifest will replay the same traces, so its compiles
    hit the persistent cache."""
    body = json.dumps(parts, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(body.encode()).hexdigest()


class StateStore:
    """The durable last-good store (see module docstring). Construction
    runs the fsck pass: quarantine anything torn or corrupt, salvage the
    valid journal prefixes, and load the surviving state. Never raises
    for on-disk damage — the worst outcome is a clean cold boot."""

    ARTIFACTS_DIR = "artifacts"
    QUARANTINE_DIR = "quarantine"
    AUDIT_DIR = "audit"
    MANIFESTS_JOURNAL = "manifests.journal"
    URLMAP_JOURNAL = "urlmap.journal"
    AUDIT_SPILL = "audit/spill.journal"
    MATRIX_SPILL = "audit/matrix.journal"
    BOOT_REPORT = "last_boot.json"
    SHARD_EVENTS = "shard_events.json"

    def __init__(self, root: str | Path):
        self.root = Path(root)
        self._lock = threading.Lock()
        # tenant -> newest valid manifest payload
        self._manifests: dict[str, dict] = {}  # guarded-by: _lock
        # tenant -> retained (generation, payload) history (newest last)
        self._manifest_history: dict[str, list[tuple[int, dict]]] = {}  # guarded-by: _lock
        # url -> {"digest": sha256-hex, "suffix": str}
        self._urlmap: dict[str, dict] = {}  # guarded-by: _lock
        self._generation = 0  # guarded-by: _lock
        # counters (the policy_server_statestore_* /metrics families)
        self._cache_hits = 0  # guarded-by: _lock
        self._cache_misses = 0  # guarded-by: _lock
        self._manifests_persisted = 0  # guarded-by: _lock
        self._fsck_quarantined = 0  # guarded-by: _lock
        self._audit_spills = 0  # guarded-by: _lock
        # newest generation durably spilled (write-ordering guard)
        self._audit_spill_gen = 0  # guarded-by: _lock
        self._audit_rows_restored = 0  # guarded-by: _lock
        self._matrix_spills = 0  # guarded-by: _lock
        # newest matrix generation durably spilled (same ordering guard)
        self._matrix_spill_gen = 0  # guarded-by: _lock
        self._matrix_cells_restored = 0  # guarded-by: _lock
        self._degraded_loads = 0  # guarded-by: _lock
        # shard fencing incidents durably logged (round 22)
        self._shard_events_recorded = 0  # guarded-by: _lock
        for sub in ("", self.ARTIFACTS_DIR, self.QUARANTINE_DIR,
                    self.AUDIT_DIR):
            (self.root / sub).mkdir(parents=True, exist_ok=True)
        self.fsck()

    # -- fsck / quarantine -------------------------------------------------

    def _quarantine(self, path: Path, reason: str) -> None:  # graftcheck: fs-atomic
        """Move a damaged file into quarantine/ (rename — the bytes are
        preserved for forensics, the boot path never sees them again)."""
        dest = (
            self.root / self.QUARANTINE_DIR
            / f"{int(time.time())}-{path.name}"
        )
        try:
            os.replace(path, dest)
        except OSError:
            return  # already gone — nothing to quarantine
        with self._lock:
            self._fsck_quarantined += 1
        logger.error(
            "statestore fsck QUARANTINED %s (%s) -> %s; boot continues on "
            "the surviving state", path, reason, dest,
        )

    def _load_journal(self, rel: str) -> list[tuple[int, dict]]:
        """Read one journal through the fsck contract: salvage the valid
        record prefix, quarantine the original when anything past it was
        corrupt, and rewrite the salvage atomically so the next boot
        reads a clean file."""
        path = self.root / rel
        try:
            data = path.read_bytes()
        except FileNotFoundError:
            return []
        except OSError as e:
            self._quarantine(path, f"unreadable: {e}")
            return []
        records, corrupt = parse_records(data)
        if corrupt:
            self._quarantine(path, "torn or corrupt record(s)")
            if records:
                atomic_write_bytes(path, frame_records(records))
                logger.warning(
                    "statestore salvaged %d valid record(s) of %s",
                    len(records), rel,
                )
        return records

    def fsck(self) -> dict[str, int]:
        """The boot-time consistency pass: load + salvage the journals,
        verify every artifact blob against its content address, and
        sweep stray temp files. Damage is quarantined and counted, never
        fatal."""
        swept = 0
        quarantine_dir = self.root / self.QUARANTINE_DIR
        for path in sorted(self.root.rglob("*")):
            if quarantine_dir in path.parents:
                continue  # already-quarantined damage is settled forever
            if ".tmp." in path.name and path.is_file():
                self._quarantine(path, "stray temp file (interrupted write)")
                swept += 1
        manifest_records = self._load_journal(self.MANIFESTS_JOURNAL)
        urlmap_records = self._load_journal(self.URLMAP_JOURNAL)
        bad_blobs = 0
        for blob in sorted((self.root / self.ARTIFACTS_DIR).iterdir()):
            if not blob.is_file():
                continue
            if blob.name.endswith(".sig.json"):
                # detached-signature sidecars are keyed by their
                # artifact's digest, not their own — verification
                # decides their fate at load time
                continue
            try:
                digest = hashlib.sha256(blob.read_bytes()).hexdigest()
            except OSError:
                digest = ""
            if digest != blob.name:
                self._quarantine(
                    blob, f"content-address mismatch (sha256={digest[:12]})"
                )
                bad_blobs += 1
        with self._lock:
            self._manifests = {}
            self._manifest_history = {}
            self._urlmap = {}
            gen = 0
            for g, payload in manifest_records:
                tenant = str(payload.get("tenant", "default"))
                hist = self._manifest_history.setdefault(tenant, [])
                hist.append((g, payload))
                self._manifests[tenant] = payload
                gen = max(gen, g)
            for g, payload in urlmap_records:
                url = payload.get("url")
                if url:
                    self._urlmap[str(url)] = {
                        "digest": payload.get("digest", ""),
                    }
                gen = max(gen, g)
            self._generation = gen
            quarantined = self._fsck_quarantined
        return {
            "quarantined": quarantined,
            "manifests": len(manifest_records),
            "urls": len(urlmap_records),
            "bad_blobs": bad_blobs,
            "stray_tmp": swept,
        }

    # -- content-addressed artifact cache ----------------------------------

    def _blob_path(self, digest: str) -> Path:
        return self.root / self.ARTIFACTS_DIR / digest

    def record_artifact(
        self, url: str, data: bytes, sidecar: bytes | None = None
    ) -> str:
        """Cache one fetched module's bytes (content-addressed) and
        journal the url→digest mapping. Returns the digest. Called by
        the module resolver on every SUCCESSFUL live fetch — the cache
        always holds exactly what last fetched cleanly. ``sidecar`` is
        the detached-signature document fetched alongside the artifact:
        it lands at ``<blob>.sig.json`` so a cache-served artifact
        verifies exactly like a live-fetched one (verification configs
        must not fail-close the warm boot). No file extension is kept —
        ``load_artifact`` dispatches on content, never the name."""
        digest = hashlib.sha256(data).hexdigest()
        blob = self._blob_path(digest)
        if not blob.exists():
            atomic_write_bytes(blob, data)
        if sidecar is not None:
            atomic_write_bytes(
                blob.with_name(blob.name + ".sig.json"), sidecar
            )
        with self._lock:
            prior = self._urlmap.get(url)
            if prior is not None and prior.get("digest") == digest:
                return digest
            self._urlmap[url] = {"digest": digest}
            self._generation += 1
            # the map IS the state: one record per url, all stamped with
            # the store generation of this rewrite (newest-wins ordering
            # only matters across generations, not within one rewrite).
            # The write happens UNDER the lock: a stale snapshot written
            # after a newer one would durably lose the newer mapping.
            records = [
                (self._generation, {"url": u, **m})
                for u, m in sorted(self._urlmap.items())
            ]
            atomic_write_bytes(
                self.root / self.URLMAP_JOURNAL, frame_records(records)
            )
        return digest

    def cached_artifact(
        self, url: str, digest: str | None = None
    ) -> Path | None:
        """Resolve a url from the cache: the blob path when the mapped
        (or explicitly pinned) digest's blob exists AND its bytes verify
        against the content address; None otherwise. An explicit
        ``digest`` (the last-good manifest's pin) needs NO url-map entry
        — the pin is authoritative even when the url journal was lost to
        quarantine, which is exactly the damage scenario the pin exists
        for. A verification failure quarantines the blob — a bit-flipped
        artifact must never load."""
        want = digest
        if want is None:
            with self._lock:
                entry = self._urlmap.get(url)
            if entry is None:
                with self._lock:
                    self._cache_misses += 1
                return None
            want = entry.get("digest", "")
        blob = self._blob_path(want)
        try:
            data = blob.read_bytes()
        except OSError:
            with self._lock:
                self._cache_misses += 1
            return None
        if hashlib.sha256(data).hexdigest() != want:
            self._quarantine(blob, "artifact bytes fail content address")
            with self._lock:
                self._cache_misses += 1
            return None
        with self._lock:
            self._cache_hits += 1
        return blob

    def count_degraded_load(self) -> None:
        """A source degraded to last-good (fetch failed, cache served)."""
        with self._lock:
            self._degraded_loads += 1

    def artifact_digests(self, urls: Iterable[str]) -> dict[str, str]:
        """url → cached digest for the urls this store knows."""
        with self._lock:
            return {
                u: self._urlmap[u]["digest"]
                for u in urls
                if u in self._urlmap
            }

    # -- per-tenant last-good epoch manifests ------------------------------

    def persist_manifest(
        self,
        tenant: str,
        *,
        epoch: int,
        outcome: str,
        policy_ids: Iterable[str],
        policies_yaml: str | None = None,
        artifact_digests: Mapping[str, str] | None = None,
        fingerprint: str | None = None,
    ) -> None:
        """Append one tenant's last-good manifest (called on every
        promotion, rollback, and boot — the rollback pin must survive a
        crash that lands one nanosecond after the epoch flip)."""
        yaml_text = policies_yaml
        payload = {
            "kind": "epoch-manifest",
            "tenant": tenant,
            "epoch": int(epoch),
            "outcome": outcome,
            "policy_ids": sorted(policy_ids),
            "policies_digest": (
                hashlib.sha256(yaml_text.encode()).hexdigest()
                if yaml_text is not None else None
            ),
            "policies_yaml": yaml_text,
            "artifact_digests": dict(artifact_digests or {}),
            "fingerprint": fingerprint,
            "time": time.time(),
        }
        with self._lock:
            self._generation += 1
            hist = self._manifest_history.setdefault(tenant, [])
            hist.append((self._generation, payload))
            del hist[:-_MANIFEST_RETENTION]
            self._manifests[tenant] = payload
            self._manifests_persisted += 1
            records = sorted(
                (rec for h in self._manifest_history.values() for rec in h),
                key=lambda r: r[0],
            )
            # write UNDER the lock: two tenants promoting concurrently
            # (one SIGHUP fans out N pipelines) must serialize the
            # journal rewrite, or the stale snapshot could land second
            # and durably drop the other tenant's fresh pin
            atomic_write_bytes(
                self.root / self.MANIFESTS_JOURNAL, frame_records(records)
            )

    def last_good_manifest(self, tenant: str = "default") -> dict | None:
        """The newest valid manifest for one tenant (None = cold)."""
        with self._lock:
            m = self._manifests.get(tenant)
            return dict(m) if m is not None else None

    def pinned_digests(
        self, tenant: str, policies_yaml: str | None
    ) -> dict[str, str]:
        """The warm-boot pin: when the CURRENT policies config is
        byte-identical to the tenant's last-good manifest, return its
        url→digest pins — the resolver then loads those artifacts from
        the cache without touching the network. A changed config returns
        no pins (live fetch is preferred; the cache stays the loud
        fallback)."""
        if policies_yaml is None:
            return {}
        manifest = self.last_good_manifest(tenant)
        if manifest is None or not manifest.get("artifact_digests"):
            return {}
        digest = hashlib.sha256(policies_yaml.encode()).hexdigest()
        if manifest.get("policies_digest") != digest:
            return {}
        return dict(manifest["artifact_digests"])

    # -- audit snapshot spill ----------------------------------------------

    def spill_audit(
        self,
        rvs: Mapping[str, str],
        fed: Mapping[str, Mapping[Any, str]],
        rows: Iterable[tuple[str, bytes]],
    ) -> int:
        """Spill the audit inventory: per-kind resourceVersion cursors,
        the watch feed's fed-object map (for DELETE synthesis after a
        resume), and every snapshot row's pre-encoded payload. The whole
        spill is ONE atomic journal replace — a crash mid-spill leaves
        the previous complete spill. Returns rows spilled."""
        head = {
            "kind": "audit-spill-head",
            "rvs": dict(rvs),
            "fed": {
                k: [[list(ok), sk] for ok, sk in mapping.items()]
                for k, mapping in fed.items()
            },
            "time": time.time(),
        }
        with self._lock:
            self._generation += 1
            gen = self._generation
        records: list[tuple[int, dict]] = [(gen, head)]
        count = 0
        for key, payload in rows:
            records.append(
                (gen, {"k": key, "p": payload.decode("utf-8")})
            )
            count += 1
        data = frame_records(records)
        with self._lock:
            # ordered by GENERATION, not lock-arrival: a slower writer
            # holding an older generation (possible during a restart
            # overlap) must never rename its stale spill over a newer
            # one — it simply discards. The expensive framing stayed
            # outside the lock.
            if gen < self._audit_spill_gen:
                return count
            atomic_write_bytes(self.root / self.AUDIT_SPILL, data)
            self._audit_spill_gen = gen
            self._audit_spills += 1
        return count

    def load_audit_spill(self) -> dict | None:
        """The spilled audit state (already fsck-salvaged at open):
        ``{"rvs": {...}, "fed": {...}, "rows": [(key, payload_bytes)]}``
        or None when no spill survived. Row records after a torn tail
        were discarded by the salvage — the watch resume re-LISTs
        whatever the spill lost."""
        records = self._load_journal(self.AUDIT_SPILL)
        if not records:
            return None
        head = records[0][1]
        if head.get("kind") != "audit-spill-head":
            return None
        rows = [
            (rec["k"], rec["p"].encode("utf-8"))
            for _g, rec in records[1:]
            if "k" in rec and "p" in rec
        ]
        with self._lock:
            self._audit_rows_restored = len(rows)
        fed = {
            k: {tuple(ok): sk for ok, sk in pairs}
            for k, pairs in (head.get("fed") or {}).items()
        }
        return {"rvs": dict(head.get("rvs") or {}), "fed": fed, "rows": rows}

    # -- verdict matrix spill (round 23, audit/matrix.py) ------------------

    def spill_matrix(
        self, head: Mapping[str, Any], cells: Iterable[Mapping[str, Any]]
    ) -> int:
        """Spill the verdict matrix next to the audit snapshot: the head
        carries the serving epoch, matrixVersion, and the COLUMN
        FINGERPRINTS (a warm boot with a different policy set invalidates
        its columns by fingerprint mismatch, never by trust); each cell
        record carries one (resource, policy) verdict plus the payload
        hash that scopes it. Same CRC-framed journal + fsck/quarantine +
        generation-ordering contract as :meth:`spill_audit`. Returns
        cells spilled."""
        with self._lock:
            self._generation += 1
            gen = self._generation
        records: list[tuple[int, dict]] = [
            (gen, {"kind": "matrix-spill-head", "time": time.time(),
                   **dict(head)})
        ]
        count = 0
        for cell in cells:
            records.append((gen, dict(cell)))
            count += 1
        data = frame_records(records)
        with self._lock:
            # generation-ordered like spill_audit: a slower writer with
            # an older generation discards rather than clobbering
            if gen < self._matrix_spill_gen:
                return count
            atomic_write_bytes(self.root / self.MATRIX_SPILL, data)
            self._matrix_spill_gen = gen
            self._matrix_spills += 1
        return count

    def load_matrix_spill(self) -> dict | None:
        """The spilled verdict matrix (fsck-salvaged like every journal):
        ``{"epoch", "version", "cols": {policy_id: fingerprint},
        "cells": [{...}]}`` or None when no spill survived. Cell records
        past a torn tail were discarded by the salvage — the boot sweep
        re-judges whatever the spill lost."""
        records = self._load_journal(self.MATRIX_SPILL)
        if not records:
            return None
        head = records[0][1]
        if head.get("kind") != "matrix-spill-head":
            return None
        cells = [rec for _g, rec in records[1:] if "k" in rec and "p" in rec]
        with self._lock:
            self._matrix_cells_restored = len(cells)
        return {
            "epoch": int(head.get("epoch", 0)),
            "version": int(head.get("version", 0)),
            "cols": dict(head.get("cols") or {}),
            "cells": cells,
        }

    # -- boot report -------------------------------------------------------

    def record_boot_report(self, report: Mapping[str, Any]) -> None:
        """Persist the boot report (warm/cold, time-to-ready, cache
        accounting) — the restart drill and operators read it from the
        state dir after the process is up."""
        atomic_write_bytes(
            self.root / self.BOOT_REPORT,
            json.dumps(dict(report), indent=1).encode(),
        )

    # -- shard incident log (round 22, runtime/shards.py) ------------------

    _SHARD_EVENTS_RETAINED = 256

    def record_shard_event(self, event: Mapping[str, Any]) -> None:
        """Append one shard fencing/respawn incident to a bounded
        on-disk log — the durable complement of the router's in-memory
        counters, so post-crash forensics can answer 'which shard died,
        when, and what happened to its rows' after the process is gone.
        Best-effort like the boot report: damage loses forensics, never
        serving."""
        path = self.root / self.SHARD_EVENTS
        with self._lock:
            try:
                events = json.loads(path.read_bytes())
                if not isinstance(events, list):
                    events = []
            except (OSError, ValueError):
                events = []
            events.append({"time": time.time(), **dict(event)})
            del events[: -self._SHARD_EVENTS_RETAINED]
            try:
                atomic_write_bytes(
                    path, json.dumps(events, indent=1).encode()
                )
                self._shard_events_recorded += 1
            except OSError:
                pass

    def shard_events(self) -> "list[dict]":
        """The retained shard incident log, oldest first (empty when
        nothing was ever fenced or the log was damaged). The durable
        read side of :meth:`record_shard_event`: router counters reset
        whenever a reload epoch or restart rebuilds the router, this
        file does not — the soak's ``shard_kill_survived`` gate counts
        incidents here."""
        path = self.root / self.SHARD_EVENTS
        with self._lock:
            try:
                events = json.loads(path.read_bytes())
            except (OSError, ValueError):
                return []
        return events if isinstance(events, list) else []

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict[str, Any]:
        artifacts = 0
        nbytes = 0
        try:
            for blob in (self.root / self.ARTIFACTS_DIR).iterdir():
                if blob.is_file():
                    if not blob.name.endswith(".sig.json"):
                        artifacts += 1
                    nbytes += blob.stat().st_size
        except OSError:
            pass
        with self._lock:
            return {
                "artifacts_resident": artifacts,
                "bytes_resident": nbytes,
                "artifact_cache_hits": self._cache_hits,
                "artifact_cache_misses": self._cache_misses,
                "manifests_persisted": self._manifests_persisted,
                "journal_records": sum(
                    len(h) for h in self._manifest_history.values()
                ) + len(self._urlmap),
                "fsck_quarantined": self._fsck_quarantined,
                "audit_spills": self._audit_spills,
                "audit_rows_restored": self._audit_rows_restored,
                "matrix_spills": self._matrix_spills,
                "matrix_cells_restored": self._matrix_cells_restored,
                "degraded_loads": self._degraded_loads,
            }
