"""``python -m policy_server_tpu`` — the process entry point
(reference src/main.rs)."""

import sys

from policy_server_tpu.config.cli import main

if __name__ == "__main__":
    sys.exit(main())
