"""In-process background audit scanner (round 10).

The reference relies on an external companion (Kubewarden's
audit-scanner) to continuously replay existing cluster resources through
the policy set; this package keeps that loop in-process, riding the
micro-batcher's best-effort audit lane so live admission traffic
strictly preempts it. See scanner.py for the full contract.
"""

from policy_server_tpu.audit.reports import PolicyReportStore
from policy_server_tpu.audit.scanner import AUDIT_MODES, AuditScanner
from policy_server_tpu.audit.snapshot import SnapshotStore, resource_key

__all__ = [
    "AUDIT_MODES",
    "AuditScanner",
    "PolicyReportStore",
    "SnapshotStore",
    "resource_key",
]
