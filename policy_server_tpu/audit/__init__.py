"""In-process background audit scanner (round 10) + live watch feed
(round 13) + persistent verdict matrix (round 23).

The reference relies on an external companion (Kubewarden's
audit-scanner) to continuously replay existing cluster resources through
the policy set; this package keeps that loop in-process, riding the
micro-batcher's best-effort audit lane so live admission traffic
strictly preempts it. See scanner.py for the full contract,
watch_feed.py for the list+watch feed that keeps the snapshot inventory
tracking a LIVE cluster instead of only /validate traffic and a seed
file, and matrix.py for the persistent (object × policy) verdict matrix
that streams verdict changes, spills through the statestore, and serves
byte-identical admissions from precomputed verdicts.
"""

from policy_server_tpu.audit.matrix import (
    VerdictMatrix,
    normalized_payload_hash,
    policy_fingerprint,
)
from policy_server_tpu.audit.reports import PolicyReportStore
from policy_server_tpu.audit.scanner import AUDIT_MODES, AuditScanner
from policy_server_tpu.audit.snapshot import (
    SnapshotStore,
    resource_key,
    synthesize_review,
)
from policy_server_tpu.audit.watch_feed import WatchFeed, parse_watch_resources

__all__ = [
    "AUDIT_MODES",
    "AuditScanner",
    "PolicyReportStore",
    "SnapshotStore",
    "VerdictMatrix",
    "WatchFeed",
    "normalized_payload_hash",
    "parse_watch_resources",
    "policy_fingerprint",
    "resource_key",
    "synthesize_review",
]
