"""In-process background audit scanner (round 10) + live watch feed
(round 13).

The reference relies on an external companion (Kubewarden's
audit-scanner) to continuously replay existing cluster resources through
the policy set; this package keeps that loop in-process, riding the
micro-batcher's best-effort audit lane so live admission traffic
strictly preempts it. See scanner.py for the full contract, and
watch_feed.py for the list+watch feed that keeps the snapshot inventory
tracking a LIVE cluster instead of only /validate traffic and a seed
file.
"""

from policy_server_tpu.audit.reports import PolicyReportStore
from policy_server_tpu.audit.scanner import AUDIT_MODES, AuditScanner
from policy_server_tpu.audit.snapshot import (
    SnapshotStore,
    resource_key,
    synthesize_review,
)
from policy_server_tpu.audit.watch_feed import WatchFeed, parse_watch_resources

__all__ = [
    "AUDIT_MODES",
    "AuditScanner",
    "PolicyReportStore",
    "SnapshotStore",
    "WatchFeed",
    "parse_watch_resources",
    "resource_key",
    "synthesize_review",
]
