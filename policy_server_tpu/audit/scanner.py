"""Background audit scanner — continuous cluster re-scan on idle device
capacity.

The reference's audit story is an external companion (Kubewarden's
audit-scanner Deployment) that periodically LISTs cluster resources and
replays them through ``POST /audit/{policy_id}``, emitting PolicyReports
— a policy set promoted today says nothing about resources admitted
under yesterday's set until the companion gets around to them. Here the
scan lives in-process and rides the serving stack's idle slots: BENCH_r05
shows the device path transport/host-bound between admission bursts, so
a background sweep is nearly free *provided live traffic strictly
preempts it*. That discipline is the micro-batcher's best-effort audit
lane (:meth:`MicroBatcher.submit_audit`): audit batches dispatch only
when the live lane is empty with RTT slack, at most one audit dispatch
is ever in flight, and a queued audit batch is re-queued (preempted) the
moment live work arrives.

Sweep cadences:

* **full sweep** — the whole snapshot store through the live epoch's
  evaluation environment; runs at scanner start, on every policy-epoch
  PROMOTION (lifecycle post-promote hook: the new set must re-judge
  everything admitted under the old one), and after a ROLLBACK (whose
  first effect is marking the rolled-back epoch's reports stale).
* **dirty sweep** — only objects served through ``/validate`` since the
  last sweep, on the ``--audit-interval-seconds`` cadence
  (``--audit-mode interval``; ``on-promote`` skips the cadence and
  sweeps only on epoch flips).

Results land in the :class:`~policy_server_tpu.audit.reports.
PolicyReportStore` stamped with the epoch generation that produced them.
Audit rows are RAW verdicts (RequestOrigin::Audit semantics —
``validation_response_with_constraints`` never applies, reference
handlers.rs:69-90), and they share the epoch's verdict cache with live
traffic, so re-scanning unchanged objects is mostly cache hits.

Degradation: while the device breaker is fully open the scanner PAUSES
(skipped sweeps are counted) instead of burning host-oracle capacity the
degraded live path needs. A mid-sweep policy reload retires the old
epoch's batcher; the in-flight audit job then fails, the sweep aborts
re-marking its unscanned keys dirty, and the post-promote hook's full
sweep picks everything up on the new epoch.

Chaos site: ``audit.sweep`` fires at the head of every sweep.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import TimeoutError as FutureTimeout
from typing import Any

from policy_server_tpu import failpoints
from policy_server_tpu.audit.reports import PolicyReportStore
from policy_server_tpu.audit.snapshot import SnapshotStore
from policy_server_tpu.telemetry.tracing import logger

AUDIT_MODES = ("off", "interval", "on-promote")


class AuditScanner:
    """The background sweeper (see module docstring). Owns a daemon
    thread; sweeps are serialized by ``_sweep_lock`` so a test-driven
    synchronous :meth:`sweep` never races the cadence thread."""

    def __init__(
        self,
        *,
        state: Any,
        snapshot: SnapshotStore,
        reports: PolicyReportStore,
        mode: str = "interval",
        interval_seconds: float = 30.0,
        batch_size: int = 256,
        job_timeout_seconds: float = 60.0,
        matrix: Any = None,
    ) -> None:
        if mode not in AUDIT_MODES:
            raise ValueError(f"invalid audit mode {mode!r}")
        self.state = state
        self.snapshot = snapshot
        self.reports = reports
        # optional verdict matrix (audit/matrix.py): when armed, sweeps
        # evaluate the dirty CROSS-PRODUCT (dirty-rows × all-columns +
        # clean-rows × dirty-columns) and feed results to the matrix
        # next to the report store; epoch hooks diff policy-content
        # fingerprints instead of requesting whole-cluster re-judges
        self.matrix = matrix
        self.mode = mode
        self.interval = max(0.05, float(interval_seconds))
        self.batch_size = max(1, int(batch_size))
        # bound on one audit-lane dispatch (queue wait behind live bursts
        # + device time); a sweep that cannot land a batch inside it
        # aborts and retries on the next cadence tick
        self.job_timeout = float(job_timeout_seconds)
        # optional live-cluster feed (audit/watch_feed.WatchFeed): set by
        # the server under --audit-watch so sweep payloads and stats
        # carry the feed's freshness accounting next to the scanner's
        self.watch_feed: Any = None
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None
        # serializes whole sweeps (cadence thread vs. test/bench callers)
        self._sweep_lock = threading.Lock()
        self._lock = threading.Lock()
        self._full_pending = True  # guarded-by: _lock — first sweep is full
        # a matrix column-diff promotion requests a DIRTY sweep; this
        # flag lets on-promote mode run it without a cadence tick
        self._kick_pending = False  # guarded-by: _lock
        self._full_sweeps = 0  # guarded-by: _lock
        self._dirty_sweeps = 0  # guarded-by: _lock
        self._sweep_errors = 0  # guarded-by: _lock
        self._paused_sweeps = 0  # guarded-by: _lock
        self._rows_scanned = 0  # guarded-by: _lock
        # whole-run accounting, segmented by the policy epoch whose set
        # judged the rows (PROFILE r13 caveat 3: one total alone reads
        # ambiguously after an epoch flip — the soak artifact needs the
        # run's full audit volume AND the per-epoch decomposition)
        self._rows_by_epoch: dict[int, int] = {}  # guarded-by: _lock
        self._last_full_sweep: float | None = None  # guarded-by: _lock

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "AuditScanner":
        if self._thread is None:
            # the pending boot sweep runs on the first loop pass, not an
            # interval later (freshness gauge live from the start)
            self._wake.set()
            self._thread = threading.Thread(
                target=self._loop, name="audit-scanner", daemon=True
            )
            self._thread.start()
        return self

    def shutdown(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self.matrix is not None:
            # final durable spill so the next boot resumes compliance
            # from the freshest verdicts, not the last cadence tick's
            self.matrix.maybe_spill(force=True)

    # -- triggers ----------------------------------------------------------

    def request_full_sweep(self, reason: str) -> None:
        with self._lock:
            self._full_pending = True
        self._wake.set()
        logger.info("audit full sweep requested (%s)", reason)

    def skip_boot_full_sweep(self) -> None:
        """Warm-boot downgrade: a successful matrix restore proved the
        covered rows current under the serving column fingerprints, so
        the pending boot FULL sweep becomes a dirty sweep of whatever
        the restore could not validate (zero re-judge of clean rows —
        the restart drill asserts this)."""
        with self._lock:
            self._full_pending = False
            self._kick_pending = True

    def request_dirty_sweep(self, reason: str) -> None:
        """Kick one dirty sweep out of cadence (matrix column-diff
        promotions: only the changed columns need re-judging, so a full
        sweep would throw away exactly the work the matrix preserved)."""
        with self._lock:
            self._kick_pending = True
        self._wake.set()
        logger.info("audit dirty sweep requested (%s)", reason)

    def _matrix_columns_sync(self, epoch: int) -> "dict | None":
        """Diff the SERVING policy set's content fingerprints into the
        matrix columns. Returns the diff, or None when the matrix is off
        or the environment cannot supply its source policies (then the
        caller falls back to the pre-matrix full-sweep contract)."""
        matrix = self.matrix
        if matrix is None:
            return None
        env = self.state.evaluation_environment
        policies = (
            getattr(env, "source_policies", None)
            if env is not None else None
        )
        if not policies:
            return None
        return matrix.set_columns(policies, epoch)

    def on_promote(self, epoch: int) -> None:
        """Lifecycle post-promote hook. Matrix off: the newly serving
        policy set must re-judge every resource admitted under the
        previous one (full sweep). Matrix on: diff column fingerprints —
        a promotion that changes 2 of 32 policies dirties 2 columns and
        kicks a dirty sweep; an unchanged-content promotion re-stamps
        cells and re-judges NOTHING."""
        diff = self._matrix_columns_sync(epoch)
        if diff is None:
            self.request_full_sweep(f"epoch-{epoch}-promoted")
            return
        if diff["dirty"] or diff["removed"]:
            self.request_dirty_sweep(
                f"epoch-{epoch}-promoted: {len(diff['dirty'])} column(s) "
                f"dirty, {len(diff['removed'])} removed"
            )

    def on_rollback(self, stale_epoch: int, serving_epoch: int) -> None:
        """Lifecycle rollback hook: the rolled-back epoch's verdicts no
        longer describe a policy set anyone serves — mark them stale,
        then re-scan under the revived epoch. The matrix diffs columns
        first (a rollback to byte-identical policy content keeps its
        cells valid; the full sweep's re-judge then re-stamps without
        emission), but the REPORT rows need the revived epoch's stamp,
        so the full-sweep contract stays."""
        marked = self.reports.mark_epoch_stale(stale_epoch)
        logger.warning(
            "audit reports from rolled-back policy epoch %d marked stale "
            "(%d rows); full re-scan under epoch %d queued",
            stale_epoch, marked, serving_epoch,
        )
        self._matrix_columns_sync(serving_epoch)
        self.request_full_sweep(f"epoch-{stale_epoch}-rolled-back")

    # -- the cadence loop --------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            # interval mode ticks on the cadence; on-promote mode sleeps
            # until a hook kicks it (short timeout only to observe stop)
            timeout = self.interval if self.mode == "interval" else 0.5
            self._wake.wait(timeout)
            self._wake.clear()
            if self._stop.is_set():
                return
            # drain observed DELETEs every tick, even when no sweep runs
            # (on-promote mode may not sweep for days; without this the
            # pending-deletion set grows unbounded under cluster churn
            # and deleted objects' report rows keep reading as current)
            self._prune_deletions()
            with self._lock:
                full = self._full_pending
                self._full_pending = False
                kick = self._kick_pending
                self._kick_pending = False
            if not full and not kick and self.mode != "interval":
                continue
            try:
                self.sweep(full=full)
            except Exception as e:  # noqa: BLE001 — the scanner must
                # survive any sweep failure (mid-sweep reload, injected
                # fault) and resume on the next trigger; sweep() already
                # re-pended the full-sweep claim
                with self._lock:
                    self._sweep_errors += 1
                logger.error("audit sweep failed (will retry): %s", e)

    # -- sweeping ----------------------------------------------------------

    def sweep(self, full: bool = True) -> int:
        """Run one sweep synchronously; returns resources×policies rows
        scanned. Public for tests and the bench harness. A full sweep
        that fails for ANY reason (injected fault, mid-sweep epoch
        retirement, job timeout) keeps its pending claim so the next
        trigger retries it."""
        with self._sweep_lock:
            try:
                return self._run_sweep(full)
            except BaseException:
                self._defer_full(full)
                raise

    def _prune_deletions(self) -> None:
        """Drain DELETE-evicted snapshot keys and drop their report rows
        (and matrix rows — each emits a DELETE changelog entry) in one
        bulk pass; called every cadence tick and at sweep heads."""
        deleted = self.snapshot.take_deletions()
        self.reports.drop_resources(deleted)
        if self.matrix is not None and deleted:
            self.matrix.evict_rows(deleted)

    def _defer_full(self, full: bool) -> None:
        """A full sweep that could not run keeps its claim: without this
        a promotion landing while the breaker is open would silently
        never re-judge the cluster under the new set (on-promote mode
        has no cadence to catch it later)."""
        if not full:
            return
        with self._lock:
            self._full_pending = True

    def _run_sweep(self, full: bool) -> int:
        # holds: _sweep_lock
        failpoints.fire("audit.sweep")
        env = self.state.evaluation_environment
        batcher = self.state.batcher
        if env is None or batcher is None:
            self._defer_full(full)
            return 0
        if getattr(env, "breaker_all_open", False):
            # open shards pause audit instead of burning the oracle
            # capacity degraded live traffic is leaning on; the pending
            # full sweep survives the pause
            with self._lock:
                self._paused_sweeps += 1
            self._defer_full(full)
            return 0
        lifecycle = getattr(self.state, "lifecycle", None)
        epoch = lifecycle.current_epoch if lifecycle is not None else 0
        # deletions observed since the last sweep prune their report
        # rows (a deleted object's verdicts must not read as current
        # cluster posture); one bulk pass, not per-key scans
        self._prune_deletions()
        matrix = self.matrix
        if matrix is not None and not matrix.has_columns():
            # standalone harnesses (bench, tests) that never fire a
            # lifecycle hook still get columns before the first record
            self._matrix_columns_sync(epoch)
        items = self.snapshot.collect(dirty_only=not full)
        policy_ids = list(env.policy_ids())
        rows = [
            (key, pid, request)
            for key, request in items
            for pid in policy_ids
        ]
        dirty_cols: set[str] = set()
        if matrix is not None:
            # the dirty CROSS-PRODUCT: dirty-rows × ALL columns (above)
            # plus clean-rows × dirty-columns. A full sweep already
            # covers every cell, so it just claims (and thereby clears)
            # the dirty-column set.
            dirty_cols = matrix.take_dirty_columns()
            col_rows = 0
            if dirty_cols and not full:
                dirty_keys = {key for key, _req in items}
                cols = [pid for pid in policy_ids if pid in dirty_cols]
                extra = [
                    (key, pid, request)
                    for key, request in self.snapshot.rows_snapshot()
                    if key not in dirty_keys
                    for pid in cols
                ]
                col_rows = len(extra)
                rows.extend(extra)
            matrix.note_sweep(
                row_rows=len(rows) - col_rows, column_rows=col_rows
            )
        scanned = 0
        try:
            for start in range(0, len(rows), self.batch_size):
                if self._stop.is_set():
                    raise RuntimeError("audit scanner shutting down")
                chunk = rows[start : start + self.batch_size]
                future = batcher.submit_audit(
                    [(pid, request) for _key, pid, request in chunk]
                )
                try:
                    results = future.result(timeout=self.job_timeout)
                except FutureTimeout:
                    # abandon the job IN THE LANE too — without this,
                    # overload-era retries would pile duplicate jobs
                    # into the deque and later burn idle dispatches on
                    # results nobody reads
                    batcher.cancel_audit(future)
                    raise RuntimeError(
                        f"audit batch timed out after "
                        f"{self.job_timeout:.0f}s waiting for an idle "
                        "slot"
                    ) from None
                report_rows = [
                    self.reports.row_from_result(
                        key, pid, request, result, epoch
                    )
                    for (key, pid, request), result in zip(chunk, results)
                ]
                self.reports.put(report_rows)
                if matrix is not None:
                    matrix.record_rows(
                        [
                            (key, pid, request, result)
                            for (key, pid, request), result in zip(
                                chunk, results
                            )
                        ],
                        epoch,
                    )
                scanned += len(chunk)
                with self._lock:
                    self._rows_scanned += len(chunk)
                    self._rows_by_epoch[epoch] = (
                        self._rows_by_epoch.get(epoch, 0) + len(chunk)
                    )
        except BaseException:
            # abort: un-judged resources go back on the dirty set so the
            # next sweep (e.g. the post-promote full sweep after a
            # mid-sweep reload killed our batcher) picks them up
            self.snapshot.remark_dirty(
                {key for key, _pid, _req in rows[scanned:]}
            )
            if matrix is not None and dirty_cols:
                # the claimed columns were not (fully) re-judged; give
                # them back so the next sweep picks them up (re-judging
                # an already-landed cell merely re-stamps, never emits)
                matrix.remark_columns_dirty(dirty_cols)
            raise
        if full:
            # a completed full sweep covered the ENTIRE inventory: any
            # report row it did not refresh describes an evicted/deleted
            # resource or a policy the serving set no longer has — prune
            # (this is what keeps the report store bounded by snapshot
            # size x policy-set size)
            self.reports.retain(
                {key for key, _pid, _req in rows}, set(policy_ids)
            )
            if matrix is not None:
                matrix.retain(
                    {key for key, _pid, _req in rows}, set(policy_ids)
                )
        with self._lock:
            if full:
                self._full_sweeps += 1
                self._last_full_sweep = time.monotonic()
            else:
                self._dirty_sweeps += 1
        if matrix is not None:
            # durability rides the sweep tail on the spill cadence (and
            # never the serving path); the scanner drives this — not the
            # watch feed — so a drill without a kube API still spills
            matrix.maybe_spill()
        return scanned

    # -- introspection -----------------------------------------------------

    def freshness_seconds(self) -> float:
        """Seconds since the last COMPLETED full sweep; -1 before the
        first one lands (the dashboard's report-freshness gauge)."""
        with self._lock:
            last = self._last_full_sweep
        if last is None:
            return -1.0
        return time.monotonic() - last

    def report_payload(self, namespace: str | None = None) -> dict[str, Any]:
        """The GET /audit/reports body: report rows + summary, plus the
        scanner's own freshness/cadence facts."""
        body = self.reports.payload(namespace)
        with self._lock:
            body["scanner"] = {
                "mode": self.mode,
                "full_sweeps": self._full_sweeps,
                "dirty_sweeps": self._dirty_sweeps,
                "sweep_errors": self._sweep_errors,
                "paused_sweeps": self._paused_sweeps,
                "rows_scanned": self._rows_scanned,
            }
        body["scanner"]["freshness_seconds"] = self.freshness_seconds()
        body["scanner"]["snapshot"] = self.snapshot.stats()
        if self.watch_feed is not None:
            body["scanner"]["watch_feed"] = self.watch_feed.stats()
        if self.matrix is not None:
            body["scanner"]["matrix"] = self.matrix.stats()
        return body

    def stats(self) -> dict[str, Any]:
        """One locked snapshot for runtime_stats (/metrics + OTLP).
        ``rows_scanned`` is the WHOLE-RUN total across every policy
        epoch; ``rows_scanned_by_epoch`` decomposes it (string epoch
        keys, JSON-artifact friendly) so a soak whose last event was an
        epoch flip still reports the run's full audit volume next to the
        post-promote sweep's share."""
        with self._lock:
            out: dict[str, Any] = {
                "full_sweeps": self._full_sweeps,
                "dirty_sweeps": self._dirty_sweeps,
                "sweep_errors": self._sweep_errors,
                "paused_sweeps": self._paused_sweeps,
                "rows_scanned": self._rows_scanned,
                "rows_scanned_by_epoch": {
                    str(e): n
                    for e, n in sorted(self._rows_by_epoch.items())
                },
            }
        out["freshness_seconds"] = self.freshness_seconds()
        if self.watch_feed is not None:
            wstats = self.watch_feed.stats()
            out["watch_events_applied"] = wstats["events_applied"]
            out["watch_events_dropped"] = wstats["events_dropped"]
            out["watch_resyncs"] = wstats["resyncs"]
        else:
            out["watch_events_applied"] = 0
            out["watch_events_dropped"] = 0
            out["watch_resyncs"] = 0
        rstats = self.reports.stats()
        out["reports_resident"] = rstats["resident"]
        out["reports_stale"] = rstats["stale"]
        sstats = self.snapshot.stats()
        out["snapshot_resources"] = sstats["resources"]
        out["snapshot_bytes"] = sstats["bytes"]
        if self.matrix is not None:
            out["matrix"] = self.matrix.stats()
        return out
