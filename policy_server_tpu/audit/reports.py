"""PolicyReport store — the audit scanner's queryable output.

Kubewarden's companion audit-scanner emits ``PolicyReport`` /
``ClusterPolicyReport`` custom resources per namespace; this in-process
build keeps the equivalent rows in memory and serves them over
``GET /audit/reports`` (cluster-wide) and
``GET /audit/reports/{namespace}``. One row per (resource, policy):
policy id, allowed, message/code, mutated flag — the RAW audit-origin
verdict, constraints never applied (reference handlers.rs:69-90).

Epoch coherence (the round-9 lifecycle contract): every row is stamped
with the policy-epoch generation whose environment produced it. A
promotion triggers a full re-scan (scanner hook), so rows refresh to the
new generation; a ROLLBACK marks every row stamped with the rolled-back
epoch ``stale`` — the verdicts were produced by a policy set the
operator just revoked, and must not be read as current cluster posture
until the post-rollback re-scan overwrites them.
"""

from __future__ import annotations

import threading
import time
from typing import Any

from policy_server_tpu.models import AdmissionResponse, ValidateRequest


class PolicyReportStore:
    """Thread-safe map of (resource key, policy id) -> latest audit
    result row. Bounded implicitly by the snapshot store's byte budget
    times the policy-set size (the scanner only writes rows for
    resources the snapshot holds)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # (resource_key, policy_id) -> report row dict
        self._rows: dict[tuple[str, str], dict[str, Any]] = {}  # guarded-by: _lock
        self._stale_marked = 0  # guarded-by: _lock
        # bumps on every mutation (put/drop/retain/mark-stale) — one of
        # the GET /audit/reports ETag axes (304 short-circuit)
        self._version = 0  # guarded-by: _lock

    @staticmethod
    def row_from_result(
        key: str,
        policy_id: str,
        request: ValidateRequest,
        result: AdmissionResponse | Exception,
        epoch: int,
    ) -> dict[str, Any]:
        """Build one report row from a replayed audit verdict. Exception
        results (unknown policy raced a reload, init error) become error
        rows rather than being dropped — an auditor must see scan
        failures, not silence."""
        adm = request.admission_request
        row: dict[str, Any] = {
            "resource": key,
            "namespace": (adm.namespace if adm else None) or "",
            "name": (adm.name if adm else None) or "",
            "kind": (adm.kind.kind if adm and adm.kind else "") or "",
            "policy_id": policy_id,
            "epoch": epoch,
            "stale": False,
            "scanned_at": time.time(),
        }
        if isinstance(result, Exception):
            row.update(
                allowed=None, mutated=False,
                message=f"audit error: {result}", code=None, error=True,
            )
            return row
        status = result.status
        row.update(
            allowed=bool(result.allowed),
            mutated=result.patch is not None,
            message=status.message if status else None,
            code=status.code if status else None,
            error=False,
        )
        return row

    def put(self, rows: list[dict[str, Any]]) -> None:
        with self._lock:
            if rows:
                self._version += 1
            for row in rows:
                self._rows[(row["resource"], row["policy_id"])] = row

    def drop_resource(self, key: str) -> None:
        """Remove every policy's row for one deleted resource."""
        self.drop_resources({key})

    def drop_resources(self, keys: set) -> int:
        """Remove every row belonging to the given resource keys in ONE
        pass over the store (the scanner drains observed DELETEs in
        bulk; a per-key scan would be O(deletions × rows)). Returns the
        number of rows dropped."""
        if not keys:
            return 0
        with self._lock:
            dead = [k for k in self._rows if k[0] in keys]
            for k in dead:
                del self._rows[k]
            if dead:
                self._version += 1
        return len(dead)

    def retain(self, resource_keys: set, policy_ids: set) -> int:
        """Post-full-sweep garbage collection: drop every row whose
        resource is no longer in the swept inventory (deleted or
        LRU-evicted) or whose policy the serving set no longer carries —
        a completed full sweep refreshed everything that still exists,
        so anything it did not touch is history. This is what actually
        bounds the store to snapshot size × policy-set size. Returns the
        number of rows dropped."""
        with self._lock:
            dead = [
                k for k in self._rows
                if k[0] not in resource_keys or k[1] not in policy_ids
            ]
            for k in dead:
                del self._rows[k]
            if dead:
                self._version += 1
        return len(dead)

    def mark_epoch_stale(self, epoch: int) -> int:
        """Rollback invalidation: every row produced by ``epoch`` is
        flagged stale (kept visible — the operator can still see WHAT
        the revoked set decided — but excluded from the pass/fail
        summary). Returns the number of rows marked."""
        marked = 0
        with self._lock:
            for row in self._rows.values():
                if row["epoch"] == epoch and not row["stale"]:
                    row["stale"] = True
                    marked += 1
            self._stale_marked += marked
            if marked:
                self._version += 1
        return marked

    # -- query surface (GET /audit/reports[/{namespace}]) ------------------

    def payload(self, namespace: str | None = None) -> dict[str, Any]:
        """The report listing plus summary counters. Stale rows are
        reported but not counted in pass/fail — they describe a policy
        set that was rolled back."""
        with self._lock:
            rows = [
                dict(row) for row in self._rows.values()
                if namespace is None or row["namespace"] == namespace
            ]
        rows.sort(key=lambda r: (r["namespace"], r["name"], r["policy_id"]))
        fresh = [r for r in rows if not r["stale"]]
        summary = {
            "results": len(rows),
            "resources": len({r["resource"] for r in rows}),
            "pass": sum(1 for r in fresh if r["allowed"] is True),
            "fail": sum(1 for r in fresh if r["allowed"] is False),
            "error": sum(1 for r in fresh if r["error"]),
            "mutated": sum(1 for r in fresh if r["mutated"]),
            "stale": len(rows) - len(fresh),
        }
        return {"summary": summary, "reports": rows}

    def version(self) -> int:
        with self._lock:
            return self._version

    def stats(self) -> dict[str, int]:
        with self._lock:
            resident = len(self._rows)
            stale = sum(1 for r in self._rows.values() if r["stale"])
        return {
            "resident": resident,
            "stale": stale,
        }
