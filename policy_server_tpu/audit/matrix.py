"""The (object × policy) verdict matrix — continuous compliance as a
persistent cross-product, and a serving accelerator.

Round 10 narrowed the audit scanner's unit of work from "whole cluster ×
whole policy set" to "dirty objects × whole policy set"; ROADMAP item 4
names the rest of the fix: make the dirty CROSS-PRODUCT the unit of
work, persist it, and let the precomputed verdicts answer admission.
This module is that subsystem:

* **rows** are cluster objects (the audit snapshot store's keys). Watch
  -feed deltas dirty rows exactly as before — ADDED/MODIFIED supersede,
  DELETED evicts the row here too (:meth:`VerdictMatrix.evict_rows`,
  driven by the scanner's deletion prune).
* **columns** are policies, keyed by a CONTENT fingerprint of the
  policy entry (module + mode + settings + members), not by epoch
  number: a promotion that changes 2 of 32 policies dirties 2 columns
  (:meth:`set_columns` diffs fingerprints), and the sweep re-judges
  dirty-rows × all-columns plus clean-rows × dirty-columns — never the
  whole cluster.
* **cells** hold the verdict fields (allowed/code/message/causes), the
  column fingerprint and normalized-payload hash that scope their
  validity, and a lazily built
  :class:`~policy_server_tpu.models.admission.FragTemplate` for the
  lookup-admission fast path.

Verdict changes append to a bounded changelog ring stamped with a
monotonic ``matrixVersion``; ``GET /audit/stream`` clients subscribe
with per-client bounded queues (:meth:`subscribe`). A slow consumer
overflows its own queue and is dropped with a counted close — the
publisher (sweep/applier side) NEVER blocks on a client. A cursor older
than the ring's tail gets a RESYNC marker plus the full current state.
Epoch promotions that leave a column's fingerprint unchanged re-stamp
cells WITHOUT emission — a promotion is not a verdict change.

Durability: verdict columns spill through the round-17 statestore next
to the audit snapshot (same CRC-framed journal + fsck/quarantine
contract, ``audit/matrix.journal``). The spill head carries the column
fingerprints, so a warm boot restores only cells whose policy content
AND object payload still match (:meth:`restore`) — a stale policy set
invalidates its columns by construction — then clears the snapshot's
dirty marks for fully covered rows so the boot sweep re-judges nothing
that is provably current.

Lookup admission (the round-19 fragment lane closed into a loop): a
``/validate`` UPDATE whose canonical encoded payload is byte-identical
(uid normalized out — the API server mints a fresh uid per review) to
the row the matrix judged, for a column whose fingerprint matches the
serving set, answers from the precomputed verdict as a pre-serialized
fragment. Eligibility is EXACTLY the fragment lane's proof
(``environment._frag_eligible``: protect mode, no mutator, no wasm,
static messages — the response is a pure function of (policy, payload)
plus the uid), and the batcher additionally requires a hookless target,
so the audit lane's raw verdict and the live constrained verdict are
provably the same bytes. Steady-state admission of unchanged objects
becomes a dict probe + hash compare (``matrix_lookup_admission`` bench
line).

Thread-safety: one lock guards all matrix state. Publishers append to
subscriber queues under the same lock; handlers drain through
:meth:`drain`. Template builds run outside the lock (GIL-atomic cell
attribute store; racing builders produce identical templates).
"""

from __future__ import annotations

import collections
import hashlib
import json
import threading
import time
from typing import Any, Iterable, Mapping

from policy_server_tpu.audit.snapshot import SnapshotStore, resource_key
from policy_server_tpu.models.admission import FragTemplate
from policy_server_tpu.telemetry.tracing import logger


def policy_fingerprint(entry: Any) -> str:
    """Content fingerprint of one policies.yml entry (Policy or
    PolicyGroup) — the column identity. Canonical JSON with sorted keys
    and sorted member/resource sets, so the hash is stable across
    processes and PYTHONHASHSEED (frozenset iteration order is not)."""
    return hashlib.sha256(
        json.dumps(
            _entry_doc(entry), sort_keys=True, separators=(",", ":")
        ).encode()
    ).hexdigest()[:16]


def _entry_doc(entry: Any) -> dict:
    def car(resources) -> list:
        return sorted(
            (r.to_dict() for r in resources),
            key=lambda d: sorted(d.items()),
        )

    if hasattr(entry, "expression"):  # PolicyGroup
        return {
            "kind": "group",
            "expression": entry.expression,
            "message": entry.message,
            "mode": entry.policy_mode.value,
            "members": {
                name: {
                    "module": m.module,
                    "settings": m.settings,
                    "car": car(m.context_aware_resources),
                }
                for name, m in entry.policies.items()
            },
        }
    return {
        "kind": "policy",
        "module": entry.module,
        "mode": entry.policy_mode.value,
        "mutate": entry.allowed_to_mutate,
        "settings": entry.settings,
        "car": car(entry.context_aware_resources),
    }


def normalized_payload_hash(request: Any) -> bytes | None:
    """Digest of the request's canonical encoded payload with the uid
    normalized out (the uid is first in ``AdmissionRequest.to_dict`` and
    compact-JSON encoded, so one bounded substring replace covers it).
    Byte-identity of this digest is the lookup-admission precondition:
    two admissions of the same object content differ only in the uid the
    API server minted. None for raw/untrackable requests."""
    adm = getattr(request, "admission_request", None)
    if adm is None:
        return None
    payload = request.payload_json()
    uid = adm.uid
    if uid:
        token = b'"uid":' + json.dumps(uid).encode()
        payload = payload.replace(token, b'"uid":""', 1)
    return hashlib.blake2b(payload, digest_size=16).digest()


class _Cell:
    """One (resource, policy) verdict plus the facts that scope its
    validity: the column fingerprint of the policy that judged it and
    the normalized payload hash of the object it judged. ``tmpl`` is the
    lazily built FragTemplate (False = proven ineligible)."""

    __slots__ = (
        "allowed", "code", "message", "causes", "epoch", "col_fp",
        "phash", "version", "tmpl",
    )

    def __init__(
        self, allowed, code, message, causes, epoch, col_fp, phash, version
    ) -> None:
        self.allowed = allowed
        self.code = code
        self.message = message
        self.causes = causes
        self.epoch = epoch
        self.col_fp = col_fp
        self.phash = phash
        self.version = version
        self.tmpl: FragTemplate | None | bool = None

    def verdict(self) -> tuple:
        return (self.allowed, self.code, self.message, self.causes)


class MatrixSubscription:
    """One /audit/stream client: a bounded queue the publisher fills
    under the matrix lock and the handler drains. Overflow marks the
    subscription dead (counted close) — the publisher never blocks."""

    __slots__ = ("queue", "dead", "resync")

    def __init__(self) -> None:
        self.queue: collections.deque = collections.deque()
        self.dead = False
        self.resync = False


class VerdictMatrix:
    """The persistent (object × policy) verdict matrix (module
    docstring). Fed by the audit scanner's sweeps, trimmed by the same
    deletion/retention passes that bound the report store, spilled
    through the statestore, and consulted by the batcher's submit paths
    for lookup admission."""

    def __init__(
        self,
        *,
        snapshot: SnapshotStore,
        statestore: Any = None,
        changelog_capacity: int = 4096,
        client_queue_capacity: int = 1024,
        spill_interval_seconds: float = 30.0,
    ) -> None:
        self.snapshot = snapshot
        self.statestore = statestore
        self.client_queue_capacity = max(16, int(client_queue_capacity))
        self.spill_interval = max(0.5, float(spill_interval_seconds))
        self._lock = threading.Lock()
        # (resource_key, policy_id) -> _Cell
        self._cells: dict[tuple[str, str], _Cell] = {}  # guarded-by: _lock
        # policy_id -> content fingerprint of the SERVING column set
        self._cols: dict[str, str] = {}  # guarded-by: _lock
        self._dirty_cols: set[str] = set()  # guarded-by: _lock
        self._epoch = 0  # guarded-by: _lock
        # monotonic matrixVersion: bumps on every emitted verdict change
        self._version = 0  # guarded-by: _lock
        self._changelog: collections.deque = collections.deque(
            maxlen=max(64, int(changelog_capacity))
        )  # guarded-by: _lock
        self._subs: list[MatrixSubscription] = []  # guarded-by: _lock
        # -- counters (runtime_stats families) ----------------------------
        self._emits = 0  # guarded-by: _lock
        self._dropped_clients = 0  # guarded-by: _lock
        self._lookup_hits = 0  # guarded-by: _lock
        self._lookup_misses = 0  # guarded-by: _lock
        self._rows_evicted = 0  # guarded-by: _lock
        self._columns_invalidated = 0  # guarded-by: _lock
        self._row_sweep_rows = 0  # guarded-by: _lock
        self._column_sweep_rows = 0  # guarded-by: _lock
        self._spills = 0  # guarded-by: _lock
        self._cells_restored = 0  # guarded-by: _lock
        self._last_spill = 0.0  # guarded-by: _lock
        self._last_whatif: dict | None = None  # guarded-by: _lock

    # -- columns (epoch lifecycle) -----------------------------------------

    def set_columns(self, policies: Mapping[str, Any], epoch: int) -> dict:
        """Install the serving policy set's columns, DIFFING content
        fingerprints against the previous set: changed/new columns are
        marked dirty (the scanner re-judges them against every row),
        removed columns evict their cells (emitted as DELETEs — the
        verdicts are withdrawn), and unchanged columns re-stamp their
        cells' epoch WITHOUT emission (a promotion is not a verdict
        change). Returns the diff for logging and the sweep planner."""
        fps = {pid: policy_fingerprint(p) for pid, p in policies.items()}
        with self._lock:
            old = self._cols
            dirty = sorted(
                pid for pid, fp in fps.items() if old.get(pid) != fp
            )
            removed = sorted(pid for pid in old if pid not in fps)
            unchanged = sorted(
                pid for pid, fp in fps.items() if old.get(pid) == fp
            )
            self._cols = fps
            self._epoch = epoch
            self._dirty_cols.update(dirty)
            self._columns_invalidated += len(dirty)
            if removed:
                gone = set(removed)
                for (key, pid) in [
                    k for k in self._cells if k[1] in gone
                ]:
                    self._cells.pop((key, pid))
                    self._emit_locked(
                        {"type": "DELETE", "resource": key, "policy": pid}
                    )
            if unchanged:
                keep = set(unchanged)
                for (key, pid), cell in self._cells.items():
                    if pid in keep:
                        cell.epoch = epoch
        if dirty or removed:
            logger.info(
                "verdict matrix columns diffed for epoch %d: %d dirty, "
                "%d removed, %d unchanged", epoch, len(dirty),
                len(removed), len(unchanged),
            )
        return {"dirty": dirty, "removed": removed, "unchanged": unchanged}

    def has_columns(self) -> bool:
        with self._lock:
            return bool(self._cols)

    def take_dirty_columns(self) -> set[str]:
        """Claim the dirty column set for one sweep (the caller re-marks
        on failure, mirroring SnapshotStore.collect/remark_dirty)."""
        with self._lock:
            out = self._dirty_cols & set(self._cols)
            self._dirty_cols = set()
            return out

    def remark_columns_dirty(self, policy_ids: Iterable[str]) -> None:
        with self._lock:
            self._dirty_cols.update(
                pid for pid in policy_ids if pid in self._cols
            )

    # -- rows ---------------------------------------------------------------

    def evict_rows(self, keys: Iterable[str]) -> int:
        """DELETE-evicted objects drop their whole matrix row; each
        resident cell emits a DELETE changelog entry."""
        keys = set(keys)
        if not keys:
            return 0
        evicted = 0
        with self._lock:
            for (key, pid) in [k for k in self._cells if k[0] in keys]:
                self._cells.pop((key, pid))
                evicted += 1
                self._emit_locked(
                    {"type": "DELETE", "resource": key, "policy": pid}
                )
            self._rows_evicted += evicted
        return evicted

    def retain(
        self, resource_keys: set[str], policy_ids: set[str]
    ) -> int:
        """Post-full-sweep GC (the report store's retain contract): any
        cell outside the swept inventory × serving policy set describes
        an evicted resource or a dropped policy — prune silently (their
        DELETEs were already emitted when observed; this is the bound,
        not the signal)."""
        with self._lock:
            stale = [
                k for k in self._cells
                if k[0] not in resource_keys or k[1] not in policy_ids
            ]
            for k in stale:
                self._cells.pop(k)
            return len(stale)

    # -- recording (the scanner's sweep results) ---------------------------

    def record_rows(
        self,
        rows: list[tuple[str, str, Any, Any]],
        epoch: int,
    ) -> None:
        """Install one sweep chunk's verdicts: ``(key, policy_id,
        request, result)`` tuples where result is an AdmissionResponse
        or an Exception. A verdict CHANGE (new cell, flipped fields)
        emits on the changelog; a re-judge that confirms the standing
        verdict re-stamps validity (epoch, payload hash, column
        fingerprint) without emission. Errors evict the cell — an
        unjudgeable pair must not keep serving a stale verdict."""
        prepared = []
        for key, pid, request, result in rows:
            if isinstance(result, Exception) or result is None:
                prepared.append((key, pid, None, None))
                continue
            phash = normalized_payload_hash(request)
            st = getattr(result, "status", None)
            causes = None
            if st is not None and st.details is not None:
                causes = tuple(
                    (c.field, c.message) for c in st.details.causes
                )
            prepared.append((
                key, pid,
                (
                    bool(result.allowed),
                    None if st is None else st.code,
                    None if st is None else st.message,
                    causes,
                ),
                phash,
            ))
        with self._lock:
            for key, pid, verdict, phash in prepared:
                if verdict is None:
                    if self._cells.pop((key, pid), None) is not None:
                        self._emit_locked(
                            {
                                "type": "DELETE", "resource": key,
                                "policy": pid,
                            }
                        )
                    continue
                col_fp = self._cols.get(pid)
                if col_fp is None:
                    continue  # column raced away mid-sweep
                cell = self._cells.get((key, pid))
                if cell is not None and cell.verdict() == verdict:
                    cell.epoch = epoch
                    if cell.phash != phash or cell.col_fp != col_fp:
                        cell.phash = phash
                        cell.col_fp = col_fp
                        cell.tmpl = None
                    continue
                allowed, code, message, causes = verdict
                self._version += 1
                self._cells[(key, pid)] = _Cell(
                    allowed, code, message, causes, epoch, col_fp,
                    phash, self._version,
                )
                self._emit_locked(
                    {
                        "type": "VERDICT",
                        "resource": key,
                        "policy": pid,
                        "allowed": allowed,
                        "code": code,
                        "message": message,
                        "epoch": epoch,
                    },
                    bumped=True,
                )

    def note_sweep(self, row_rows: int = 0, column_rows: int = 0) -> None:
        """Sweep-planner accounting: rows judged because their ROW was
        dirty vs rows judged because their COLUMN was dirty — the two
        axes of the cross-product, kept separate so the dashboard shows
        which axis the cluster's churn is actually exercising."""
        with self._lock:
            self._row_sweep_rows += row_rows
            self._column_sweep_rows += column_rows

    # -- changelog / stream -------------------------------------------------

    def _emit_locked(self, entry: dict, bumped: bool = False) -> None:
        # holds: _lock
        if not bumped:
            self._version += 1
        entry["matrixVersion"] = self._version
        self._changelog.append(entry)
        self._emits += 1
        cap = self.client_queue_capacity
        for sub in self._subs:
            if sub.dead:
                continue
            if len(sub.queue) >= cap:
                # slow consumer: drop the CLIENT, never block or trim
                # its view into silent gaps — the counted close tells it
                # to reconnect with its cursor and resync honestly
                sub.dead = True
                self._dropped_clients += 1
                continue
            sub.queue.append(entry)

    def subscribe(self, cursor: int | None) -> MatrixSubscription:
        """Register a stream client. ``cursor`` is the last
        matrixVersion the client saw (None = new client, live tail
        only). A cursor the changelog ring still covers replays exactly
        the missed entries; an older cursor gets a RESYNC marker plus
        the full current state, stamped with each cell's own version."""
        sub = MatrixSubscription()
        with self._lock:
            if cursor is not None and cursor < self._version:
                tail_v = (
                    self._changelog[0]["matrixVersion"]
                    if self._changelog else self._version + 1
                )
                if cursor >= tail_v - 1:
                    for e in self._changelog:
                        if e["matrixVersion"] > cursor:
                            sub.queue.append(e)
                else:
                    sub.resync = True
                    sub.queue.append(
                        {"type": "RESYNC", "matrixVersion": self._version}
                    )
                    for (key, pid), cell in sorted(
                        self._cells.items(), key=lambda kv: kv[1].version
                    ):
                        sub.queue.append(
                            {
                                "type": "VERDICT",
                                "resource": key,
                                "policy": pid,
                                "allowed": cell.allowed,
                                "code": cell.code,
                                "message": cell.message,
                                "epoch": cell.epoch,
                                "matrixVersion": cell.version,
                            }
                        )
            self._subs.append(sub)
        return sub

    def unsubscribe(self, sub: MatrixSubscription) -> None:
        with self._lock:
            try:
                self._subs.remove(sub)
            except ValueError:
                pass

    def drain(self, sub: MatrixSubscription) -> tuple[list[dict], bool]:
        """Pop everything queued for one client; returns (entries,
        dead). A dead subscription's drained tail still delivers — the
        close is counted, not silent."""
        with self._lock:
            out = list(sub.queue)
            sub.queue.clear()
            return out, sub.dead

    def stream_clients(self) -> int:
        with self._lock:
            return sum(1 for s in self._subs if not s.dead)

    # -- lookup admission ---------------------------------------------------

    def lookup(self, policy_id: str, request: Any, env: Any):
        """The precomputed verdict for a byte-identical admission, as a
        FragTemplate — or None (counted miss). The caller (batcher) has
        already proven the target hookless and the origin VALIDATE; this
        method proves payload identity (normalized hash), column
        currency (fingerprint match), and fragment eligibility (the
        round-19 proof, memoized per cell)."""
        key = resource_key(request)
        if key is None:
            with self._lock:
                self._lookup_misses += 1
            return None
        phash = normalized_payload_hash(request)
        with self._lock:
            cell = self._cells.get((key, policy_id))
            if (
                cell is None
                or cell.phash != phash
                or cell.col_fp != self._cols.get(policy_id)
            ):
                self._lookup_misses += 1
                return None
            tmpl = cell.tmpl
        if tmpl is None:
            tmpl = self._build_template(policy_id, cell, env)
        if tmpl is False:
            with self._lock:
                self._lookup_misses += 1
            return None
        with self._lock:
            self._lookup_hits += 1
        return tmpl

    def _build_template(self, policy_id: str, cell: _Cell, env: Any):
        """Build (or refuse) the cell's FragTemplate outside the lock:
        eligibility is the fragment lane's own proof, so a template only
        exists where the audit verdict and the live constrained verdict
        are the same pure function of (policy, payload). GIL-atomic
        store; racing builders produce identical templates."""
        from policy_server_tpu.evaluation.policy_id import PolicyID

        try:
            target = env._lookup_top_level(  # noqa: SLF001 — same package
                PolicyID.parse(policy_id)
            )
            eligible = env._frag_eligible(target)  # noqa: SLF001 — same package
        except Exception:  # noqa: BLE001 — unknown id / stale env
            eligible = False
        if not eligible:
            cell.tmpl = False
            return False
        try:
            tmpl = FragTemplate(
                allowed=cell.allowed,
                code=cell.code,
                message=cell.message,
                causes=cell.causes,
            )
        except UnicodeEncodeError:
            # json can represent what utf-8 cannot encode (lone
            # surrogates) — permanently Python-rendered, never a hit
            cell.tmpl = False
            return False
        cell.tmpl = tmpl
        return tmpl

    # -- durability (round-17 statestore) -----------------------------------

    def maybe_spill(self, force: bool = False) -> bool:
        """Spill the matrix through the statestore when the cadence (or
        ``force``) says so. Called from the scanner's sweep tail and the
        server's shutdown path — never from the serving hot path."""
        store = self.statestore
        if store is None:
            return False
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_spill < self.spill_interval:
                return False
            self._last_spill = now
            head = {
                "epoch": self._epoch,
                "version": self._version,
                "cols": dict(self._cols),
            }
            cells = [
                {
                    "k": key,
                    "p": pid,
                    "a": cell.allowed,
                    "c": cell.code,
                    "m": cell.message,
                    "x": cell.causes,
                    "e": cell.epoch,
                    "f": cell.col_fp,
                    "h": cell.phash.hex() if cell.phash else None,
                    "v": cell.version,
                }
                for (key, pid), cell in self._cells.items()
            ]
            self._spills += 1
        store.spill_matrix(head, cells)
        return True

    def restore(self) -> int:
        """Warm-boot restore: install spilled cells whose column
        fingerprint still matches the SERVING policy set (a stale set
        invalidates its columns by construction) and whose payload hash
        still matches the restored snapshot row (a changed object must
        be re-judged). Rows covered for EVERY serving column get their
        snapshot dirty mark cleared — the boot sweep then re-judges
        nothing that is provably current. Call AFTER set_columns and
        after the snapshot is restored/seeded."""
        store = self.statestore
        if store is None:
            return 0
        spill = store.load_matrix_spill()
        if spill is None:
            return 0
        row_hashes = {
            key: normalized_payload_hash(req)
            for key, req in self.snapshot.rows_snapshot()
        }
        installed = 0
        with self._lock:
            self._version = max(self._version, int(spill.get("version", 0)))
            for c in spill.get("cells", []):
                key, pid = c.get("k"), c.get("p")
                fp = c.get("f")
                if self._cols.get(pid) != fp:
                    continue  # policy content changed since the spill
                h = bytes.fromhex(c["h"]) if c.get("h") else None
                if h is None or row_hashes.get(key) != h:
                    continue  # object changed (or gone) since the spill
                causes = c.get("x")
                self._cells[(key, pid)] = _Cell(
                    bool(c.get("a")), c.get("c"), c.get("m"),
                    tuple(tuple(x) for x in causes) if causes else None,
                    int(c.get("e", 0)), fp, h,
                    int(c.get("v", 0)) or self._version,
                )
                installed += 1
            self._cells_restored += installed
            # an fp-matched column's verdicts are restored wherever the
            # payload still matches; rows that changed stayed DIRTY (the
            # snapshot restore dirtied them), so the column itself needs
            # no whole-cluster re-judge
            spill_cols = spill.get("cols") or {}
            self._dirty_cols -= {
                pid for pid, fp in self._cols.items()
                if spill_cols.get(pid) == fp
            }
            cols = set(self._cols)
            covered = [
                key for key in row_hashes
                if cols and all(
                    (key, pid) in self._cells for pid in cols
                )
            ]
        if covered:
            self.snapshot.clear_dirty(covered)
        if installed:
            logger.info(
                "verdict matrix restored from the state-store spill",
                extra={"span_fields": {
                    "cells": installed, "covered_rows": len(covered),
                }},
            )
        return installed

    # -- what-if (stretch, behind --audit-matrix-whatif) --------------------

    def whatif_diff(
        self, candidate_env: Any, policies: Mapping[str, Any],
        max_rows: int = 256,
    ) -> dict:
        """Cluster-wide shadow canary: evaluate a CANDIDATE epoch's
        CHANGED columns against the live snapshot (bounded) and diff the
        verdicts against the standing matrix — canarying over the whole
        cluster, not a request ring. Returns (and retains, for the
        reload status surface) a summary with a sample of flips."""
        fps = {pid: policy_fingerprint(p) for pid, p in policies.items()}
        with self._lock:
            changed = sorted(
                pid for pid, fp in fps.items()
                if self._cols.get(pid) != fp
            )
        rows = self.snapshot.rows_snapshot()[:max_rows]
        pairs = [
            (key, pid, req)
            for key, req in rows
            for pid in changed
            if pid in candidate_env.policy_ids()
        ]
        flips: list[dict] = []
        evaluated = 0
        for start in range(0, len(pairs), 128):
            chunk = pairs[start:start + 128]
            results = candidate_env.validate_batch(
                [(pid, req) for _k, pid, req in chunk], run_hooks=False
            )
            for (key, pid, _req), result in zip(chunk, results):
                evaluated += 1
                if isinstance(result, Exception):
                    continue
                allowed = bool(result.allowed)
                with self._lock:
                    cell = self._cells.get((key, pid))
                before = None if cell is None else cell.allowed
                if before is not None and before != allowed and len(
                    flips
                ) < 32:
                    flips.append(
                        {
                            "resource": key, "policy": pid,
                            "was_allowed": before, "would_allow": allowed,
                        }
                    )
        summary = {
            "columns_changed": changed,
            "rows_evaluated": evaluated,
            "verdict_flips": len(flips),
            "flips_sample": flips,
        }
        with self._lock:
            self._last_whatif = summary
        return summary

    def last_whatif(self) -> dict | None:
        with self._lock:
            return self._last_whatif

    # -- introspection -------------------------------------------------------

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def serving_epoch(self) -> int:
        with self._lock:
            return self._epoch

    def coverage(self) -> tuple[int, int]:
        """(distinct matrix rows, rows with a cell for EVERY serving
        column) — the soak convergence gate's parity facts."""
        with self._lock:
            cols = set(self._cols)
            rows: dict[str, int] = {}
            for (key, _pid) in self._cells:
                rows[key] = rows.get(key, 0) + 1
            complete = sum(
                1 for n in rows.values() if cols and n >= len(cols)
            )
            return len(rows), complete

    def stats(self) -> dict[str, Any]:
        with self._lock:
            rows = {key for (key, _pid) in self._cells}
            return {
                "rows_resident": len(rows),
                "cells_resident": len(self._cells),
                "columns": len(self._cols),
                "dirty_columns": len(self._dirty_cols),
                "matrix_version": self._version,
                "changelog_emits": self._emits,
                "changelog_dropped_clients": self._dropped_clients,
                "stream_clients": sum(
                    1 for s in self._subs if not s.dead
                ),
                "lookup_hits": self._lookup_hits,
                "lookup_misses": self._lookup_misses,
                "rows_evicted": self._rows_evicted,
                "columns_invalidated": self._columns_invalidated,
                "row_sweep_rows": self._row_sweep_rows,
                "column_sweep_rows": self._column_sweep_rows,
                "spills": self._spills,
                "cells_restored": self._cells_restored,
            }

    def cells_snapshot(self) -> dict[tuple[str, str], tuple]:
        """Verdict fields per cell — the bit-exactness witness the tests
        and the soak parity gate compare against a full re-sweep."""
        with self._lock:
            return {
                k: cell.verdict() for k, cell in self._cells.items()
            }
