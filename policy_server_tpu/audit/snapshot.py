"""Snapshot store — the audit scanner's view of the cluster.

The reference delegates continuous re-scanning to an external companion
(Kubewarden's audit-scanner) that LISTs cluster resources and replays
them through ``POST /audit/{policy_id}``. This build keeps the scan
in-process, so it needs its own resource inventory. Two feeds populate
it:

* **Dirty-set tracking** — every object served through ``/validate`` is
  recorded per formed batch by :class:`~policy_server_tpu.runtime.
  batcher.MicroBatcher` (the same one-call-per-batch discipline as the
  round-9 shadow-canary ring), keyed by GVK + namespace + name so a
  later admission of the same object SUPERSEDES the earlier snapshot —
  the store always holds the newest served generation. A ``DELETE``
  admission evicts the key (the object is gone; re-auditing it would
  report on a resource the cluster no longer has).
* **File seeding** (``--audit-resources-file``) — a YAML/JSON list of
  Kubernetes objects (or a ``List``-style ``{items: [...]}`` document)
  synthesized into CREATE admission reviews, the stand-in for the
  companion scanner's initial cluster LIST when no traffic has been
  served yet.

Rows are kept payload-encoded (``ValidateRequest.payload_json`` is
memoized, and the live path computed it already), so a sweep re-submits
pre-encoded rows and the verdict-cache/dedup tiers make re-scans of
unchanged objects nearly free. Memory is bounded by
``--audit-max-snapshot-bytes`` with LRU eviction on the recording order.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Iterable

from policy_server_tpu.models import (
    AdmissionRequest,
    GroupVersionKind,
    ValidateRequest,
)
from policy_server_tpu.telemetry.tracing import logger


def synthesize_review(
    obj: Any, operation: str = "CREATE", uid: str | None = None
) -> ValidateRequest | None:
    """One Kubernetes object → a synthetic admission review the snapshot
    store can record: the stand-in for the review the API server would
    have sent had this object been admitted through the webhook. Used by
    file seeding (CREATE rows) and by the live watch feed (ADDED →
    CREATE, MODIFIED → UPDATE, DELETED → DELETE — the DELETE shape only
    needs the identity fields; :meth:`SnapshotStore.observe` evicts on
    it without storing the payload). Returns ``None`` for objects with
    no usable kind."""
    if not isinstance(obj, dict) or "kind" not in obj:
        return None
    api_version = obj.get("apiVersion", "v1") or "v1"
    group, _, version = api_version.rpartition("/")
    meta = obj.get("metadata") or {}
    gvk = GroupVersionKind(
        group=group, version=version, kind=obj.get("kind", "")
    )
    uid = uid or meta.get("uid") or f"audit-synth-{id(obj):x}"
    name = meta.get("name") or uid
    req = AdmissionRequest(
        uid=uid,
        kind=gvk,
        name=name,
        namespace=meta.get("namespace"),
        operation=operation,
        user_info={"username": "system:policy-server-audit"},
        object=None if operation == "DELETE" else obj,
        dry_run=True,
    )
    return ValidateRequest.from_admission(req)


def resource_key(request: ValidateRequest) -> str | None:
    """GVK + namespace + name identity of the object an admission review
    targets; ``None`` for rows the store cannot track (raw requests,
    nameless reviews with no uid to fall back on)."""
    adm = request.admission_request
    if adm is None:
        return None
    kind = adm.kind or GroupVersionKind()
    name = adm.name or adm.uid
    if not name:
        return None
    return "/".join(
        (kind.group, kind.version, kind.kind, adm.namespace or "", name)
    )


class SnapshotStore:
    """Bounded, dirty-tracking inventory of cluster resources as
    admission requests (see module docstring). Thread-safe: the
    micro-batcher records from its dispatch workers while the scanner
    collects from its sweep thread."""

    def __init__(self, max_bytes: int = 64 * 1024 * 1024):
        self.max_bytes = max(0, int(max_bytes))
        self._lock = threading.Lock()
        # key -> (request, nbytes); insertion order is the LRU axis
        self._rows: collections.OrderedDict[
            str, tuple[ValidateRequest, int]
        ] = collections.OrderedDict()  # guarded-by: _lock
        self._dirty: set[str] = set()  # guarded-by: _lock
        # keys evicted by an observed DELETE since the last sweep — the
        # scanner drains these to prune the objects' report rows
        self._pending_deletions: set[str] = set()  # guarded-by: _lock
        self._bytes = 0  # guarded-by: _lock
        self._recorded = 0  # guarded-by: _lock
        self._superseded = 0  # guarded-by: _lock
        self._evicted = 0  # guarded-by: _lock
        self._deleted = 0  # guarded-by: _lock
        # bumps on every mutating observe — the /audit/reports ETag axis
        self._generation = 0  # guarded-by: _lock

    # -- recording (the batcher's dirty-set tracker) -----------------------

    def observe(self, requests: Iterable[ValidateRequest]) -> None:
        """Record a batch of served ``/validate`` requests. Called once
        per formed batch from the dispatch worker — sizes are computed
        OUTSIDE the lock (payload_json is memoized; the encoder reuses
        it, so this is not wasted work)."""
        prepared: list[tuple[str, ValidateRequest | None, int]] = []
        for request in requests:
            key = resource_key(request)
            if key is None:
                continue
            adm = request.admission_request
            if adm is not None and (adm.operation or "").upper() == "DELETE":
                prepared.append((key, None, 0))
                continue
            prepared.append((key, request, len(request.payload_json())))
        if not prepared:
            return
        with self._lock:
            self._generation += 1
            for key, request, nbytes in prepared:
                if request is None:
                    old = self._rows.pop(key, None)
                    if old is not None:
                        self._bytes -= old[1]
                        self._deleted += 1
                    self._dirty.discard(key)
                    self._pending_deletions.add(key)
                    continue
                self._pending_deletions.discard(key)  # re-created object
                old = self._rows.pop(key, None)
                if old is not None:
                    self._bytes -= old[1]
                    self._superseded += 1
                self._rows[key] = (request, nbytes)
                self._bytes += nbytes
                self._recorded += 1
                self._dirty.add(key)
            self._evict_over_budget_locked()

    def _evict_over_budget_locked(self) -> None:
        # holds: _lock
        if self.max_bytes <= 0:
            return
        while self._bytes > self.max_bytes and self._rows:
            key, (_req, nbytes) = self._rows.popitem(last=False)
            self._bytes -= nbytes
            self._dirty.discard(key)
            self._evicted += 1

    # -- durable spill (round 17, statestore.py) ---------------------------

    def export_rows(self) -> list[tuple[str, bytes]]:
        """One locked snapshot of the inventory as ``(key, payload_json)``
        pairs — the audit-spill corpus (payload_json is memoized, so this
        is serialization-free for rows the live path already encoded)."""
        with self._lock:
            items = list(self._rows.items())
        return [(key, req.payload_json()) for key, (req, _n) in items]

    def restore_rows(self, pairs: Iterable[tuple[str, bytes]]) -> int:
        """Rebuild inventory rows from a spill's pre-encoded payloads (a
        warm boot's snapshot seed — the watch feed then RESUMES from its
        spilled resourceVersion instead of re-LISTing the cluster).
        Undecodable rows are skipped loudly; the next full re-LIST
        repairs whatever a damaged spill lost."""
        import json as _json

        restored: list[ValidateRequest] = []
        skipped = 0
        for _key, payload in pairs:
            try:
                req = AdmissionRequest.from_dict(_json.loads(payload))
                restored.append(ValidateRequest.from_admission(req))
            except Exception:  # noqa: BLE001 — a damaged row must not
                skipped += 1  # fail the boot; the resync repairs it
        self.observe(restored)
        if skipped:
            logger.warning(
                "audit spill restore skipped %d undecodable row(s); the "
                "next full re-LIST resync repairs the inventory", skipped,
            )
        return len(restored)

    # -- seeding -----------------------------------------------------------

    def seed_from_file(self, path: str) -> int:
        """Load a YAML/JSON resources file (a list of objects or a
        ``{items: [...]}`` List document) and record one synthetic
        CREATE review per object. Returns the number of rows seeded."""
        import yaml

        with open(path, "r", encoding="utf-8") as f:
            doc = yaml.safe_load(f)
        if isinstance(doc, dict) and "items" in doc:
            objects = doc["items"]
        elif isinstance(doc, list):
            objects = doc
        else:
            raise ValueError(
                f"audit resources file {path!r} must hold a list of "
                "objects or a List document with an 'items' field"
            )
        seeded = 0
        batch: list[ValidateRequest] = []
        for i, obj in enumerate(objects):
            req = synthesize_review(obj, "CREATE", uid=f"audit-seed-{i}")
            if req is not None:
                batch.append(req)
                seeded += 1
        self.observe(batch)
        logger.info(
            "audit snapshot seeded from resources file",
            extra={"span_fields": {"path": path, "resources": seeded}},
        )
        return seeded

    # -- collection (the scanner's sweep feed) -----------------------------

    def collect(
        self, dirty_only: bool = False
    ) -> list[tuple[str, ValidateRequest]]:
        """Snapshot the sweep corpus and clear the dirty set: the FULL
        inventory, or only the keys touched since the last collect.
        A failed sweep re-marks its unscanned keys via
        :meth:`remark_dirty` so the next sweep picks them back up."""
        with self._lock:
            if dirty_only:
                keys = [k for k in self._dirty if k in self._rows]
            else:
                keys = list(self._rows)
            self._dirty.clear()
            return [(k, self._rows[k][0]) for k in keys]

    def remark_dirty(self, keys: Iterable[str]) -> None:
        with self._lock:
            self._dirty.update(k for k in keys if k in self._rows)

    def clear_dirty(self, keys: Iterable[str]) -> int:
        """Drop dirty marks for rows proven current by other means — the
        verdict matrix's warm-boot restore clears the marks its restored
        columns fully cover, so the boot sweep re-judges nothing that is
        provably up to date."""
        with self._lock:
            before = len(self._dirty)
            self._dirty.difference_update(keys)
            return before - len(self._dirty)

    def rows_snapshot(self) -> list[tuple[str, ValidateRequest]]:
        """The full inventory WITHOUT clearing dirty marks — the verdict
        matrix's row axis (clean-rows × dirty-columns sweeps and warm-
        boot payload-hash validation read this; :meth:`collect` remains
        the only consumer that claims the dirty set)."""
        with self._lock:
            return [(k, row[0]) for k, row in self._rows.items()]

    def dirty_keys(self) -> set[str]:
        with self._lock:
            return set(self._dirty)

    def take_deletions(self) -> set[str]:
        """Drain the keys evicted by observed DELETEs since the last
        call — the scanner prunes their report rows."""
        with self._lock:
            out = self._pending_deletions
            self._pending_deletions = set()
            return out

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "resources": len(self._rows),
                "bytes": self._bytes,
                "dirty": len(self._dirty),
                "generation": self._generation,
                "recorded": self._recorded,
                "superseded": self._superseded,
                "evicted": self._evicted,
                "deleted": self._deleted,
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)
