"""Live-cluster watch feed for the audit snapshot store.

Until round 13 the audit scanner's cluster inventory came from two
approximations of reality: /validate dirty-tracking (only objects that
happened to flow through the webhook) and a boot-time seed file
(``--audit-resources-file``). The reference's audit companion instead
LISTs the live cluster. This module closes that gap in-process: it runs
the SAME list+watch state machine the context service uses
(:func:`~policy_server_tpu.context.service.run_watch_loop` —
resourceVersion resume on clean stream close, 410/transport-fault
re-LIST with backoff, interval resync bounding staleness) and folds the
events straight into the :class:`~policy_server_tpu.audit.snapshot.
SnapshotStore`:

* **ADDED / MODIFIED** → a synthetic CREATE/UPDATE admission review;
  the store's supersede semantics keep only the newest generation.
* **DELETED** → a synthetic DELETE review; the store evicts the key and
  queues it for report pruning (the scanner's ``take_deletions`` drain).
* **full re-LIST** (resync, 410, recovery after an overflow) → the
  fresh inventory supersedes in bulk, and every key this feed
  previously fed that is ABSENT from the new LIST gets a synthetic
  DELETE — a deletion that happened while the stream was down must not
  leave a ghost report row.

Queueing is BOUNDED and loud: watcher threads (one per kind) push
events onto one bounded queue drained by a single applier thread (the
payload-encoding work of ``observe`` must not stall the HTTP streams).
When the queue is full the event is DROPPED, counted, and the kind's
watcher raises — forcing a full re-LIST resync, so a drop can delay
freshness but never corrupt the inventory. Every resync is counted per
reason (``expired`` / ``error`` / ``interval``).

Chaos site: ``watch.stream`` fires before every watch-stream connect —
a raise there exercises exactly the transport-fault resync path.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Iterable

from policy_server_tpu import failpoints
from policy_server_tpu.audit.snapshot import (
    SnapshotStore,
    resource_key as snapshot_key,
    synthesize_review,
)
from policy_server_tpu.context.service import run_watch_loop, resource_key
from policy_server_tpu.models.policy import ContextAwareResource
from policy_server_tpu.telemetry.tracing import logger


class _QueueOverflow(Exception):
    """Raised into the watch loop when the bounded event queue is full:
    the loop treats it like a transport fault — backoff, then a full
    re-LIST that repairs whatever the dropped events would have done."""


def parse_watch_resources(spec: str) -> tuple[ContextAwareResource, ...]:
    """``"v1/Pod,apps/v1/Deployment"`` → ContextAwareResource tuple (the
    --audit-watch-resources flag format: apiVersion/Kind per entry)."""
    out = []
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        api_version, _, kind = entry.rpartition("/")
        if not api_version or not kind:
            raise ValueError(
                f"malformed watch resource {entry!r} "
                "(expected apiVersion/Kind, e.g. v1/Pod or "
                "apps/v1/Deployment)"
            )
        out.append(ContextAwareResource(api_version=api_version, kind=kind))
    return tuple(out)


class WatchFeed:
    """Owns the per-kind watcher threads + the applier thread feeding the
    snapshot store (see module docstring). ``fetcher`` is anything with
    the ``list_with_version(resource)`` / ``watch(resource, rv)``
    protocol — the in-cluster :class:`KubeApiFetcher`, or a synthetic
    cluster (tools/soak, tests)."""

    # applier drains up to this many events into ONE observe() call
    APPLY_CHUNK = 512

    def __init__(
        self,
        fetcher: Any,
        resources: Iterable[ContextAwareResource],
        snapshot: SnapshotStore,
        *,
        refresh_seconds: float = 30.0,
        max_queue_events: int = 65536,
        resync_multiplier: int = 10,
        statestore: Any = None,
        spill_interval_seconds: float = 30.0,
        resume_rvs: dict[str, str] | None = None,
        resume_fed: dict[str, dict[tuple, str]] | None = None,
    ) -> None:
        self.fetcher = fetcher
        self.resources = tuple(resources)
        self.snapshot = snapshot
        self.refresh_seconds = float(refresh_seconds)
        self.max_queue_events = max(1, int(max_queue_events))
        self.resync_multiplier = int(resync_multiplier)
        # durable audit spill (round 17, statestore.py): a DEDICATED
        # spiller thread periodically writes the per-kind resourceVersion
        # cursors + the fed-object map + the snapshot inventory, so a
        # restarted process RESUMES the watch streams (resume_rvs/
        # resume_fed seed the loops) instead of re-LISTing the whole
        # cluster. Off the applier thread on purpose: serializing a
        # 100k-row inventory must never stall event application into a
        # queue-overflow re-LIST. None = no --state-dir, bit-identical
        # pre-round-17 behavior.
        self.statestore = statestore
        self.spill_interval_seconds = float(spill_interval_seconds)
        self._resume_rvs = dict(resume_rvs or {})
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._cond = threading.Condition()
        # ("event", kind_key, etype, obj, rv) | ("replace", kind_key,
        # items, rv)
        self._queue: collections.deque = collections.deque()  # guarded-by: _cond
        # per kind: object identity -> snapshot-store key, for DELETE
        # synthesis on replace (applier-written; the spiller copies it,
        # so mutations AND copies hold the lock)
        self._fed: dict[str, dict[tuple, str]] = dict(resume_fed or {})  # guarded-by: _cond
        # per kind: the LIST rv the watcher last announced (watcher-
        # thread confined per kind; attached to the queued replace)
        self._list_rvs: dict[str, str] = {}  # graftcheck: lockfree — per-kind watcher-thread-confined
        # per kind: newest APPLIED resourceVersion — the spill cursor.
        # Advanced only after the snapshot observed the event/LIST, so a
        # spill can never persist a cursor ahead of its inventory (a
        # crash between would silently skip those events on resume).
        self._rvs: dict[str, str] = dict(resume_rvs or {})  # guarded-by: _cond
        self._events_applied = 0  # guarded-by: _cond
        self._events_dropped = 0  # guarded-by: _cond
        self._resyncs = 0  # guarded-by: _cond
        self._resync_reasons: dict[str, int] = {}  # guarded-by: _cond
        self._streams_opened = 0  # guarded-by: _cond
        self._replaces = 0  # guarded-by: _cond
        self._deletes_synthesized = 0  # guarded-by: _cond
        self._spills = 0  # guarded-by: _cond
        self._resumed_kinds = len(self._resume_rvs)  # graftcheck: lockfree — set once pre-start

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "WatchFeed":
        if self._threads:
            return self
        applier = threading.Thread(
            target=self._apply_loop, name="audit-watch-apply", daemon=True
        )
        applier.start()
        self._threads.append(applier)
        if self.statestore is not None:
            spiller = threading.Thread(
                target=self._spill_loop, name="audit-spill", daemon=True
            )
            spiller.start()
            self._threads.append(spiller)
        for r in self.resources:
            t = threading.Thread(
                target=self._watch_one,
                args=(r,),
                name=f"audit-watch-{resource_key(r)}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        logger.info(
            "audit watch feed started",
            extra={"span_fields": {
                "kinds": [resource_key(r) for r in self.resources],
                "max_queue_events": self.max_queue_events,
            }},
        )
        return self

    def stop(self) -> None:
        self._stop.set()
        # a watcher blocked inside fetcher.watch() only observes _stop
        # between events; fetchers that support it (SyntheticCluster)
        # close their streams so shutdown does not ride out the joins.
        # The in-cluster fetcher's streams have a bounded read timeout,
        # so its daemon watchers die on their own.
        close = getattr(self.fetcher, "close_streams", None)
        if close is not None:
            close()
        with self._cond:
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=5)
        self._threads = []

    # -- watcher side ------------------------------------------------------

    def _watch_one(self, resource: ContextAwareResource) -> None:
        def on_stream() -> None:
            with self._cond:
                self._streams_opened += 1
            failpoints.fire("watch.stream")

        def on_resync(key: str, reason: str) -> None:
            with self._cond:
                self._resyncs += 1
                self._resync_reasons[reason] = (
                    self._resync_reasons.get(reason, 0) + 1
                )
            logger.warning(
                "audit watch feed resynced %s via full re-LIST (%s)",
                key, reason,
            )

        def on_rv(key: str, rv: str) -> None:
            # announced before replace_kind on the same watcher thread:
            # _enqueue_replace attaches it to the queued LIST, and the
            # cursor only ADVANCES when the applier lands the inventory
            self._list_rvs[key] = rv

        run_watch_loop(
            self.fetcher,
            resource,
            stop=self._stop,
            refresh_seconds=self.refresh_seconds,
            replace_kind=self._enqueue_replace,
            apply_event=self._enqueue_event,
            # a spilled resourceVersion RESUMES the watch where the
            # crashed process left off (no boot LIST); a stale cursor
            # degrades to the loop's standard 410/error re-LIST path.
            # None = the loop's first pass does the boot LIST.
            rv=self._resume_rvs.get(resource_key(resource)),
            resync_multiplier=self.resync_multiplier,
            on_resync=on_resync,
            on_stream=on_stream,
            on_rv=on_rv,
        )

    def _enqueue_event(self, key: str, etype: str, obj: Any) -> None:
        rv = ((obj.get("metadata") or {}).get("resourceVersion")
              if isinstance(obj, dict) else None)
        with self._cond:
            if len(self._queue) >= self.max_queue_events:
                self._events_dropped += 1
                # raising into run_watch_loop forces the full re-LIST
                # that repairs whatever this drop lost — loud, bounded,
                # never silently stale
                raise _QueueOverflow(
                    f"watch event queue full ({self.max_queue_events}); "
                    f"dropping {etype} for {key} and forcing a resync"
                )
            self._queue.append(
                ("event", key, etype, obj, str(rv) if rv else None)
            )
            self._cond.notify()

    def _enqueue_replace(self, key: str, items: Iterable[Any]) -> None:
        items = tuple(items)
        with self._cond:
            # a replace supersedes every queued event of this kind —
            # purging them guarantees space and keeps per-kind ordering
            self._queue = collections.deque(
                e for e in self._queue if e[1] != key
            )
            self._queue.append(
                ("replace", key, items, self._list_rvs.get(key))
            )
            self._cond.notify()

    # -- applier side ------------------------------------------------------

    def _apply_loop(self) -> None:
        while True:
            batch: list = []
            with self._cond:
                while not self._queue and not self._stop.is_set():
                    self._cond.wait(timeout=0.5)
                if self._stop.is_set() and not self._queue:
                    return
                while self._queue and len(batch) < self.APPLY_CHUNK:
                    batch.append(self._queue.popleft())
            try:
                self._apply_batch(batch)
            except Exception as e:  # noqa: BLE001 — the feed must survive
                # any malformed object; the interval resync re-LISTs the
                # truth eventually
                logger.error("audit watch feed apply failed: %s", e)

    # -- spiller side ------------------------------------------------------

    def _spill_loop(self) -> None:
        while not self._stop.wait(self.spill_interval_seconds):
            self._spill_once()
        # final spill on clean shutdown so the next boot resumes from
        # the freshest possible cursor
        self._spill_once()

    def _spill_once(self) -> None:
        """One durable spill: cursor map + fed map + the whole snapshot
        inventory, one atomic journal replace. The cursors are copied
        BEFORE the inventory export, so concurrent application can only
        leave the inventory AHEAD of the cursor — the resume then
        replays overlapping events, which the store's supersede
        semantics absorb; a cursor ahead of its inventory (silently
        skipped events) is impossible by construction. Contained — a
        full disk degrades durability, never the feed."""
        if self.statestore is None:
            return
        try:
            with self._cond:
                rvs = dict(self._rvs)
                fed = {k: dict(m) for k, m in self._fed.items()}
            self.statestore.spill_audit(
                rvs, fed, self.snapshot.export_rows()
            )
            with self._cond:
                self._spills += 1
        except Exception as e:  # noqa: BLE001 — durability is best-effort
            logger.error("audit spill failed: %s", e)

    def _apply_batch(self, batch: list) -> None:
        from policy_server_tpu.context.service import _object_key

        reviews: list = []
        applied = 0
        deletes = 0
        # kind -> newest rv in this batch's EVENT entries; committed to
        # the spill cursor only after the final observe below lands the
        # buffered reviews in the snapshot
        event_rvs: dict[str, str] = {}
        for entry in batch:
            if entry[0] == "replace":
                # flush ordered work queued before this replace first
                if reviews:
                    self.snapshot.observe(reviews)
                    reviews = []
                _kind, key, items, list_rv = entry
                reviews_r, deletes_r = self._replace_reviews(key, items)
                self.snapshot.observe(reviews_r)
                deletes += deletes_r
                with self._cond:
                    self._replaces += 1
                    self._deletes_synthesized += deletes_r
                    if list_rv:
                        # the LIST is now fully applied: the cursor may
                        # advance past everything it superseded
                        self._rvs[key] = list_rv
                event_rvs.pop(key, None)
                continue
            _tag, key, etype, obj, rv = entry
            op = {
                "ADDED": "CREATE",
                "MODIFIED": "UPDATE",
                "DELETED": "DELETE",
            }.get(etype)
            if op is None:
                continue
            review = synthesize_review(obj, op)
            if review is None:
                continue
            okey = _object_key(obj)
            skey = snapshot_key(review)
            with self._cond:
                fed = self._fed.setdefault(key, {})
                if op == "DELETE":
                    fed.pop(okey, None)
                elif skey is not None:
                    fed[okey] = skey
            if rv:
                event_rvs[key] = rv
            reviews.append(review)
            applied += 1
        if reviews:
            self.snapshot.observe(reviews)
        with self._cond:
            if applied:
                self._events_applied += applied
            # every buffered review is in the snapshot now: commit the
            # batch's event cursors
            self._rvs.update(event_rvs)

    def _replace_reviews(self, key: str, items: tuple) -> tuple[list, int]:
        """A full LIST for one kind → CREATE reviews for the inventory
        plus synthetic DELETEs for previously-fed objects that vanished
        while the stream was down (their report rows must prune)."""
        from policy_server_tpu.context.service import _object_key

        with self._cond:
            fed = dict(self._fed.get(key) or {})
        fresh: dict[tuple, str] = {}
        reviews: list = []
        for obj in items:
            review = synthesize_review(obj, "CREATE")
            if review is None:
                continue
            skey = snapshot_key(review)
            if skey is not None:
                fresh[_object_key(obj)] = skey
            reviews.append(review)
        deletes = 0
        fresh_skeys = set(fresh.values())
        for okey, skey in fed.items():
            if okey in fresh:
                continue
            # deleted-and-RE-CREATED during the outage: the uid changed
            # but the same GVK/ns/name is alive in the fresh LIST — the
            # store is name-keyed, so a synthetic DELETE here would
            # evict the live row the CREATE above just recorded
            if skey in fresh_skeys:
                continue
            # identity + kind fields are recoverable from the store key:
            # group/version/kind/namespace/name
            group, version, kind, ns, name = skey.split("/", 4)
            obj = {
                "apiVersion": f"{group}/{version}" if group else version,
                "kind": kind,
                "metadata": {"name": name, "namespace": ns or None},
            }
            review = synthesize_review(obj, "DELETE")
            if review is not None:
                reviews.append(review)
                deletes += 1
        with self._cond:
            self._fed[key] = fresh
        return reviews, deletes

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict[str, Any]:
        with self._cond:
            return {
                "events_applied": self._events_applied,
                "events_dropped": self._events_dropped,
                "resyncs": self._resyncs,
                "resync_reasons": dict(self._resync_reasons),
                "streams_opened": self._streams_opened,
                "replaces": self._replaces,
                "deletes_synthesized": self._deletes_synthesized,
                "queue_depth": len(self._queue),
                "spills": self._spills,
                "resumed_kinds": self._resumed_kinds,
            }
