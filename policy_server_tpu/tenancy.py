"""Multi-tenant serving — N policy-sets on one fleet with hard
noisy-neighbor isolation (round 16).

The reference deploys one Deployment per PolicyServer CR, so tenant
isolation is free but the fleet multiplies. Here the epoch machinery
(lifecycle.py) already gives ONE policy set an isolated environment
(its own XLA programs, verdict cache, and circuit breaker) plus its
own micro-batcher; this module generalizes epochs into named
**tenants** so one process — and one accelerator mesh — serves many
clusters:

* **Tenants manifest** (``--tenants tenants.yml``)::

      tenants:
        team-a:
          policies: team-a-policies.yml   # relative to the manifest
          weight: 2.0                     # weighted-fair dispatch share
          quota-rows-per-second: 500      # token bucket; 0 = unlimited
          quota-burst: 250                # bucket depth (default: rate)
          max-inflight: 512               # admitted-unresolved cap; 0 = off
          request-timeout-ms: 5000        # per-tenant deadline class
          degraded-mode: reject           # per-tenant breaker fallback
      default:                            # optional default-tenant knobs
        weight: 1.0
        quota-rows-per-second: 0
      max-concurrent-dispatches: 4        # FairDispatchScheduler cap

* **Per-tenant epoch lifecycle.** Every tenant owns a full
  :class:`~policy_server_tpu.lifecycle.PolicyLifecycleManager` over its
  own policies file: independent digest watch, SIGHUP-triggered reload,
  shadow canary, rollback, and epoch pinning — one tenant's poisoned
  canary rolls back THAT tenant only, and its verdict cache / breaker /
  canary ring can never observe another tenant's state (they live in
  the tenant's environments).

* **Admission quotas.** :class:`TenantAdmission` is a token bucket
  (rows/s + burst) plus an in-flight cap, consulted by the tenant's
  batcher at every submit; a denied admission answers HTTP 429 +
  Retry-After and increments tenant-labelled shed counters, so one
  tenant's overload storm sheds at ITS front door instead of queueing
  into shared capacity.

* **Weighted-fair dispatch.** All tenant batchers share one
  :class:`~policy_server_tpu.runtime.scheduler.FairDispatchScheduler`
  (live > per-tenant weighted shares > audit, runtime/scheduler.py).

* **Routing.** ``POST /validate/{tenant}/{policy_id}`` (and the audit /
  raw variants) picks the tenant from the path; every existing URL maps
  to the reserved ``default`` tenant, so single-tenant deployments are
  bit-identical to round 15. ``GET /readiness/{tenant}`` answers that
  tenant's honest readiness; the global probe degrades only when EVERY
  tenant is degraded.

Device angle: every tenant's policy set lowers over the SAME device
fleet — with a ``policy`` mesh axis each tenant's set packs across the
axis as its own fused SPMD program with its own verdict slice, so N
reference Deployments collapse onto one accelerator mesh that
time-shares the tenants' programs.

Failpoints: ``tenant.reload`` (per-tenant policies re-read) and
``tenant.admission`` (quota check head) — both honor the thread-scoped
arming in failpoints.py so chaos can fault ONE tenant.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Mapping

import yaml

from policy_server_tpu import failpoints
from policy_server_tpu.runtime.batcher import ShedError

#: the reserved tenant name every existing (un-prefixed) URL routes to
DEFAULT_TENANT = "default"

# names that would shadow literal routes (/audit/reports/...) or the
# reserved default; rejected at manifest parse, not at serve time
_RESERVED_TENANT_NAMES = frozenset({DEFAULT_TENANT, "reports", "stream"})


def unknown_tenant_message(name: str) -> str:
    """The ONE 404 body text for an unknown tenant — shared by the
    aiohttp handlers and the native frontend's sink so both frontends
    answer byte-identically."""
    return f"unknown tenant: {name}"


class TenantConfigError(ValueError):
    """Malformed tenants manifest."""


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's parsed manifest entry."""

    name: str
    policies_path: str | None = None  # None only for the default tenant
    weight: float = 1.0
    quota_rows_per_second: float = 0.0  # 0 = unlimited
    quota_burst: float = 0.0  # 0 = default to one second of rate
    max_inflight: int = 0  # 0 = uncapped
    request_timeout_ms: float | None = None  # None = server default
    degraded_mode: str | None = None  # None = server default

    def validate(self) -> None:
        if not self.name or "/" in self.name:
            raise TenantConfigError(
                f"invalid tenant name {self.name!r} (must be non-empty, "
                "no '/')"
            )
        if self.name in _RESERVED_TENANT_NAMES and self.name != DEFAULT_TENANT:
            raise TenantConfigError(
                f"tenant name {self.name!r} is reserved (it would shadow "
                "a literal route)"
            )
        if self.weight <= 0:
            raise TenantConfigError(
                f"tenant {self.name!r}: weight must be > 0"
            )
        if self.quota_rows_per_second < 0 or self.quota_burst < 0:
            raise TenantConfigError(
                f"tenant {self.name!r}: quota values must be >= 0"
            )
        if self.max_inflight < 0:
            raise TenantConfigError(
                f"tenant {self.name!r}: max-inflight must be >= 0"
            )
        if self.degraded_mode is not None and self.degraded_mode not in (
            "oracle", "monitor", "reject"
        ):
            raise TenantConfigError(
                f"tenant {self.name!r}: invalid degraded-mode "
                f"{self.degraded_mode!r}"
            )


@dataclass
class TenantManifest:
    """The parsed tenants file: named tenant specs, optional overrides
    for the reserved default tenant, and the shared scheduler cap."""

    tenants: dict[str, TenantSpec] = field(default_factory=dict)
    default: TenantSpec = field(
        default_factory=lambda: TenantSpec(name=DEFAULT_TENANT)
    )
    max_concurrent_dispatches: int = 4


def _spec_from_doc(name: str, doc: Mapping, base_dir: Path) -> TenantSpec:
    if not isinstance(doc, Mapping):
        raise TenantConfigError(
            f"tenant {name!r}: entry must be a mapping, got "
            f"{type(doc).__name__}"
        )
    known = {
        "policies", "weight", "quota-rows-per-second", "quota-burst",
        "max-inflight", "request-timeout-ms", "degraded-mode",
    }
    unknown = set(doc) - known
    if unknown:
        raise TenantConfigError(
            f"tenant {name!r}: unknown keys {sorted(unknown)} "
            f"(expected {sorted(known)})"
        )
    policies = doc.get("policies")
    if policies is not None:
        p = Path(policies)
        if not p.is_absolute():
            p = base_dir / p
        policies = str(p)
    rt = doc.get("request-timeout-ms")
    spec = TenantSpec(
        name=name,
        policies_path=policies,
        weight=float(doc.get("weight", 1.0)),
        quota_rows_per_second=float(doc.get("quota-rows-per-second", 0.0)),
        quota_burst=float(doc.get("quota-burst", 0.0)),
        max_inflight=int(doc.get("max-inflight", 0)),
        request_timeout_ms=None if rt is None else float(rt),
        degraded_mode=doc.get("degraded-mode"),
    )
    spec.validate()
    return spec


def read_tenants_file(path: str | Path) -> TenantManifest:
    """Parse a tenants manifest (see module docstring for the shape).
    Relative per-tenant policies paths resolve against the manifest's
    own directory — the manifest is self-contained wherever it lives."""
    path = Path(path)
    with open(path, "r", encoding="utf-8") as f:
        doc = yaml.safe_load(f) or {}
    if not isinstance(doc, Mapping):
        raise TenantConfigError("tenants file must be a YAML mapping")
    unknown = set(doc) - {"tenants", "default", "max-concurrent-dispatches"}
    if unknown:
        raise TenantConfigError(
            f"unknown top-level keys {sorted(unknown)} in tenants file"
        )
    base_dir = path.resolve().parent
    tenants_doc = doc.get("tenants") or {}
    if not isinstance(tenants_doc, Mapping) or not tenants_doc:
        raise TenantConfigError(
            "tenants file must define at least one tenant under 'tenants:'"
        )
    tenants: dict[str, TenantSpec] = {}
    for name, entry in tenants_doc.items():
        name = str(name)
        if name in _RESERVED_TENANT_NAMES:
            raise TenantConfigError(
                f"tenant name {name!r} is reserved (the default tenant is "
                "configured under the top-level 'default:' key)"
            )
        spec = _spec_from_doc(name, entry or {}, base_dir)
        if spec.policies_path is None:
            raise TenantConfigError(
                f"tenant {name!r}: 'policies' is required"
            )
        tenants[name] = spec
    default_doc = doc.get("default") or {}
    default = _spec_from_doc(DEFAULT_TENANT, default_doc, base_dir)
    if default.policies_path is not None:
        raise TenantConfigError(
            "the default tenant's policies come from --policies, not the "
            "tenants manifest"
        )
    cap = int(doc.get("max-concurrent-dispatches", 4))
    if cap < 1:
        raise TenantConfigError("max-concurrent-dispatches must be >= 1")
    return TenantManifest(
        tenants=tenants, default=default, max_concurrent_dispatches=cap
    )


def split_tenant_path(policy_id: str) -> tuple[str | None, str]:
    """``"tenant/policy"`` → ``("tenant", "policy")``; a bare policy id
    → ``(None, policy_id)``. The native frontend routes two-segment
    evaluation paths through here so both frontends agree."""
    tenant, sep, rest = policy_id.partition("/")
    if not sep:
        return None, policy_id
    return tenant, rest


def lookup_tenant(state: Any, name: str):
    """The ONE tenant-registry lookup every surface uses (aiohttp
    handlers, readiness probe, native sink, prefork bridge): the
    :class:`Tenant` for ``name``, or None when unknown — including
    every name on a deployment with no tenants manifest."""
    mgr = getattr(state, "tenants", None)
    return mgr.get(name) if mgr is not None else None


def resolve_tenant_batcher(state: Any, policy_id: str):
    """Resolve a wire policy id (possibly ``"tenant/policy"``) to the
    serving batcher: ``(batcher, bare_policy_id, None)``, or
    ``(None, bare_policy_id, unknown_tenant_name)`` — the caller
    packages the 404 for its transport with
    :func:`unknown_tenant_message`, so resolution RULES live in exactly
    one place and the frontends stay byte-identical by construction."""
    tenant, pid = split_tenant_path(policy_id)
    if tenant is None:
        return state.batcher, policy_id, None
    t = lookup_tenant(state, tenant)
    if t is None:
        return None, pid, tenant
    return t.state.batcher, pid, None


class TenantAdmission:
    """Per-tenant admission quota: a token bucket over admitted ROWS
    (refilled continuously at ``rows_per_second`` up to ``burst``) plus
    an in-flight cap on admitted-but-unresolved rows. Denials raise
    :class:`~policy_server_tpu.runtime.batcher.ShedError` with an
    honest Retry-After derived from the refill rate — the webhook
    caller can actually use it. Cheap by construction: one lock, a few
    float ops, called once per submit burst (never per row on the bulk
    path).

    Composition across serving shards (round 22, runtime/shards.py):
    ONE TenantAdmission instance fronts a tenant's whole shard set —
    admission happens before routing, so the quota is tenant-global no
    matter how many shards serve the tenant. The in-flight cap relies
    on the batcher's exactly-once release discipline: a row's
    ``quota_token`` travels WITH the row when a fenced shard's queue is
    re-routed to a sibling (no re-admission — the row was already
    paid for) and is released by whichever resolution fires first
    (verdict, 503 fence, 504 deadline). A shard kill therefore never
    leaks inflight slots and never double-releases them."""

    def __init__(
        self,
        tenant: str,
        rows_per_second: float = 0.0,
        burst: float = 0.0,
        max_inflight: int = 0,
    ) -> None:
        self.tenant = tenant
        self.rate = max(0.0, float(rows_per_second))
        self.burst = float(burst) if burst > 0 else max(1.0, self.rate)
        self.max_inflight = max(0, int(max_inflight))
        self._lock = threading.Lock()
        self._tokens = self.burst  # guarded-by: _lock
        self._refilled_at = time.monotonic()  # guarded-by: _lock
        self._inflight = 0  # guarded-by: _lock
        # tenant-labelled counters (/metrics)
        self._admitted_rows = 0  # guarded-by: _lock
        self._quota_sheds = 0  # guarded-by: _lock
        self._inflight_sheds = 0  # guarded-by: _lock

    def admit(self, n: int = 1) -> None:
        """Admit ``n`` rows or raise ShedError. The chaos site fires
        FIRST so an armed ``tenant.admission`` fault is an admission-
        layer fault (in-band error), not a quota answer; it fires under
        THIS tenant's scope (admission runs on handler threads that
        carry no ambient scope, so the quota sets its own)."""
        with failpoints.scope(self.tenant):
            failpoints.fire("tenant.admission")
        with self._lock:
            if self.max_inflight and self._inflight + n > self.max_inflight:
                self._inflight_sheds += n
                raise ShedError(0.05)
            if self.rate > 0:
                now = time.monotonic()
                self._tokens = min(
                    self.burst,
                    self._tokens + (now - self._refilled_at) * self.rate,
                )
                self._refilled_at = now
                # a burst larger than the bucket DEPTH must still be
                # admittable (the native frontend admits whole poll
                # bursts as units): require only a full bucket's worth
                # up front and let the balance go into deficit — later
                # admissions shed until the deficit repays at ``rate``,
                # so the average rate stays bounded and the advertised
                # Retry-After is a wait that can actually succeed
                need = min(float(n), self.burst)
                if self._tokens < need:
                    self._quota_sheds += n
                    raise ShedError((need - self._tokens) / self.rate)
                self._tokens -= n
            self._inflight += n
            self._admitted_rows += n

    def release(self, n: int = 1) -> None:
        """A previously admitted row resolved (any outcome). Floored at
        zero: a rare double-resolution during shutdown's self-drain
        must never wedge the cap negative-side."""
        with self._lock:
            self._inflight = max(0, self._inflight - n)

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "admitted_rows": self._admitted_rows,
                "quota_sheds": self._quota_sheds,
                "inflight_sheds": self._inflight_sheds,
                "inflight": self._inflight,
                "shed_rows": self._quota_sheds + self._inflight_sheds,
            }


@dataclass
class TenantState:
    """A named tenant's epoch pointer — the duck-typed analog of
    :class:`~policy_server_tpu.api.state.ApiServerState` that the
    lifecycle manager rebinds on promotion/rollback (the default
    tenant's pointer IS the ApiServerState, so existing deployments
    are untouched)."""

    name: str
    evaluation_environment: Any = None
    batcher: Any = None
    ready: bool = False
    lifecycle: Any = None

    def readiness(self) -> tuple[int, str]:
        from policy_server_tpu.api.state import readiness_verdict

        return readiness_verdict(
            self.ready, self.batcher, self.evaluation_environment
        )


class Tenant:
    """One serving tenant: its spec, its epoch pointer (state), and its
    admission quota. ``state`` is a :class:`TenantState` for named
    tenants and the process ApiServerState for the default tenant."""

    def __init__(
        self, name: str, spec: TenantSpec, state: Any,
        admission: TenantAdmission | None,
    ) -> None:
        self.name = name
        self.spec = spec
        self.state = state
        self.admission = admission

    @property
    def lifecycle(self):
        return self.state.lifecycle

    def readiness(self) -> tuple[int, str]:
        """THIS tenant's honest verdict — always computed from the raw
        epoch-pointer fields (for the default tenant, ``state`` is the
        ApiServerState whose own readiness() is the process-wide
        AGGREGATE; calling it here would recurse)."""
        from policy_server_tpu.api.state import readiness_verdict

        s = self.state
        return readiness_verdict(
            getattr(s, "ready", True),
            s.batcher,
            s.evaluation_environment,
        )

    def request_reload(self, reason: str) -> bool:
        lc = self.state.lifecycle
        if lc is None:
            return False
        return lc.request_reload(reason)


class TenantManager:
    """The tenant registry: name → :class:`Tenant`, including the
    reserved default. Built once at bootstrap; the mapping is immutable
    afterwards (tenant onboarding is a restart — per-tenant POLICY
    changes hot-reload through each tenant's lifecycle)."""

    def __init__(
        self, scheduler: Any = None
    ) -> None:
        self.scheduler = scheduler
        self._tenants: dict[str, Tenant] = {}  # immutable post-bootstrap

    def add(self, tenant: Tenant) -> None:
        self._tenants[tenant.name] = tenant

    def get(self, name: str) -> Tenant | None:
        return self._tenants.get(name)

    def named(self) -> list[Tenant]:
        """Every tenant EXCEPT the default (whose epoch stack the server
        owns through its own lifecycle/teardown paths)."""
        return [
            t for t in self._tenants.values() if t.name != DEFAULT_TENANT
        ]

    def all(self) -> list[Tenant]:
        return list(self._tenants.values())

    # -- aggregate readiness (the partial-outage contract) ----------------

    def any_ready(self) -> bool:
        return any(t.readiness()[0] == 200 for t in self._tenants.values())

    def degraded_names(self) -> list[str]:
        return [
            t.name for t in self._tenants.values()
            if t.readiness()[0] != 200
        ]

    # -- fan-out operations ------------------------------------------------

    def reload_all(self, reason: str) -> int:
        """Kick a background reload on every tenant that has a
        lifecycle (the SIGHUP contract: one signal reloads certs, the
        default policy set, and every named tenant — each pipeline
        independent, each failure contained to its tenant)."""
        started = 0
        for t in self._tenants.values():
            if t.request_reload(reason):
                started += 1
        return started

    def shutdown(self) -> None:
        """Tear down every NAMED tenant's epoch stack (the default
        tenant's lifecycle is shut down by the server, which owns it)."""
        for t in self.named():
            lc = t.state.lifecycle
            if lc is not None:
                try:
                    lc.shutdown()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
            else:
                try:
                    t.state.batcher.shutdown()
                except Exception:  # noqa: BLE001
                    pass
                try:
                    t.state.evaluation_environment.close()
                except Exception:  # noqa: BLE001
                    pass

    # -- the /metrics surface ---------------------------------------------

    def stats(self) -> dict[str, Any]:
        """Tenant-labelled sample lists for the runtime-stats collector:
        ``{family_key: [((tenant,), value), ...]}`` plus the serving
        count. One pass; each underlying read is its owner's one-lock
        snapshot."""
        sched_stats = (
            self.scheduler.stats() if self.scheduler is not None else {}
        )
        shed, admitted, inflight = [], [], []
        queue_depth, grants, wait_s = [], [], []
        epoch, rollbacks, ready = [], [], []
        for name, t in self._tenants.items():
            key = (name,)
            adm = (
                t.admission.stats() if t.admission is not None else None
            )
            if adm is not None:
                shed.append((key, float(adm["shed_rows"])))
                admitted.append((key, float(adm["admitted_rows"])))
                inflight.append((key, float(adm["inflight"])))
            batcher = t.state.batcher
            if batcher is not None:
                queue_depth.append((key, float(batcher.queue_depth())))
            ss = sched_stats.get(name)
            if ss is not None:
                grants.append((key, float(ss["grants"])))
                wait_s.append((key, ss["wait_ns"] / 1e9))
            lc = t.state.lifecycle
            if lc is not None:
                ls = lc.stats()
                epoch.append((key, float(ls["epoch"])))
                rollbacks.append((key, float(ls["rollbacks"])))
            ready.append(
                (key, 1.0 if t.readiness()[0] == 200 else 0.0)
            )
        return {
            "shed_rows": shed,
            "admitted_rows": admitted,
            "inflight_rows": inflight,
            "queue_depth": queue_depth,
            "dispatch_grants": grants,
            "dispatch_wait_seconds": wait_s,
            "epoch": epoch,
            "rollbacks": rollbacks,
            "ready": ready,
            "serving": len(self._tenants),
        }
