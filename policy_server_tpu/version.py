"""Version info.

Reference parity: the reference exposes its version via clap
(/root/reference/src/cli.rs:23-27) and a ``--long-version`` banner listing
the OPA builtins (cli.rs:7-21). See ``policy_server_tpu.config.cli``.
"""

__version__ = "0.1.0"
