"""PolicyServer — bootstrap pipeline and run loop.

Reference parity: src/lib.rs —
* ``PolicyServer::new_from_config`` (lib.rs:75-236): trust root → download →
  precompile → evaluation environment → state → TLS → routers. Here the
  pipeline is: fetch/resolve modules → build + typecheck IR programs →
  fused-program warmup (the rayon precompile analog, lib.rs:287-307) →
  micro-batcher → aiohttp routers.
* ``PolicyServer::run`` (lib.rs:238-280): API server and readiness server
  run concurrently; readiness binds only AFTER the API server is up
  (Notify handshake, lib.rs:239-268).

The wasmtime epoch ticker (lib.rs:176-190) has no analog here: the batcher
enforces the request deadline directly (runtime/batcher.py)."""

from __future__ import annotations

import asyncio
import ssl
from typing import Callable

from aiohttp import web

from policy_server_tpu.api import profiling
from policy_server_tpu.api.handlers import build_readiness_router, build_router
from policy_server_tpu.api.state import ApiServerState
from policy_server_tpu.config.config import Config
from policy_server_tpu.evaluation.environment import (
    EvaluationEnvironment,
    EvaluationEnvironmentBuilder,
)
from policy_server_tpu.evaluation.precompiled import PolicyModule
from policy_server_tpu.runtime.batcher import MicroBatcher
from policy_server_tpu.telemetry import setup_metrics
from policy_server_tpu.telemetry import metrics as metrics_names
from policy_server_tpu.telemetry.tracing import logger


class _PendingRespawn:
    """Placeholder in the worker-process table for a slot whose respawn is
    delayed by crash-loop backoff (its previous process has been reaped)."""

    def __init__(self, returncode):
        self.returncode = returncode

    def poll(self):  # duck-type subprocess.Popen for liveness checks
        return self.returncode


class PolicyServer:
    """The bootstrapped server (reference PolicyServer, lib.rs:64-72)."""

    def __init__(
        self,
        config: Config,
        state: ApiServerState,
        tls_context: ssl.SSLContext | None,
    ) -> None:
        self.config = config
        self.state = state  # carries the serving epoch's env + batcher
        self.tls_context = tls_context
        self._ready = asyncio.Event()
        self._runners: list[web.AppRunner] = []
        self.api_port: int | None = None
        self.readiness_port: int | None = None
        # prefork HTTP frontend state (runtime/frontend.py)
        self._bridge = None
        self._worker_procs: list = []
        self._bridge_socket: str | None = None
        # native HTTP frontend (runtime/native_frontend.py); None under
        # --frontend python or after a native-load fallback
        self._native_frontend = None
        # native TLS termination manager (NativeTlsManager); None under
        # plaintext, --native-tls off, or the aiohttp-TLS fallback
        self._native_tls = None
        # self-heal watchdog (supervision.py): rebuilds a wedged batcher
        # dispatch loop / frontend drainer; started with the servers
        self._selfheal = None

    # The serving environment/batcher are the CURRENT EPOCH's — a hot
    # reload (lifecycle.py) rebinds the state fields, so everything that
    # reads them through the server (tests, stop(), logging) follows the
    # promoted epoch automatically.
    @property
    def environment(self) -> EvaluationEnvironment:
        return self.state.evaluation_environment

    @property
    def batcher(self) -> MicroBatcher:
        return self.state.batcher

    @property
    def lifecycle(self):
        return self.state.lifecycle

    # -- bootstrap (lib.rs:75-236) -----------------------------------------

    @classmethod
    def new_from_config(
        cls,
        config: Config,
        module_resolver: Callable[[str], PolicyModule] | None = None,
    ) -> "PolicyServer":
        import time as _time

        boot_t0 = _time.monotonic()
        if config.enable_metrics:
            registry = setup_metrics()
            # Reference pushes metrics over OTLP gRPC (metrics.rs:14-29).
            # Here push activates when a collector endpoint is configured;
            # the Prometheus pull endpoint stays on either way (fallback
            # that also removes a collector hop from the serving path).
            import os as _os

            from policy_server_tpu.telemetry import otlp as _otlp

            if _os.environ.get(_otlp.ENDPOINT_ENV):
                _otlp.install_metrics_pusher(registry)
        # flight recorder (round 18, telemetry/flightrec.py): installed
        # BEFORE any batcher/environment is built so warmup dispatches
        # already record. Always on by default; the phase histogram
        # feeds the process-wide metrics registry (one funnel: /metrics
        # pull + OTLP push).
        from policy_server_tpu.telemetry import flightrec as _flightrec

        if config.flight_recorder:
            from policy_server_tpu.telemetry import default_registry as _dr

            _flightrec.install(
                _flightrec.FlightRecorder(
                    capacity=config.recorder_ring_events,
                    row_sample_rate=config.recorder_row_sample_rate,
                    registry=_dr(),
                )
            )
        else:
            _flightrec.install(None)
        if config.enable_pprof:
            profiling.activate_memory_profiling()
            if config.http_workers > 1:
                logger.warning(
                    "--enable-pprof with --http-workers: the pprof routes "
                    "are served by the main process only; a fraction of "
                    "connections on the shared port land on workers and "
                    "404 — hit the endpoint repeatedly or set "
                    "--http-workers 1 when profiling"
                )
        if config.compilation_cache_dir:
            # persistent XLA compilation cache: warmed policy programs
            # survive restarts (SURVEY.md §5 checkpoint/resume row)
            import jax

            jax.config.update(
                "jax_compilation_cache_dir", config.compilation_cache_dir
            )
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        if config.distributed_coordinator:
            # Multi-host bring-up BEFORE any device enumeration: the mesh
            # built below must span every process's devices (SURVEY.md §7.2
            # step 10; ICI within a slice, DCN across slices).
            from policy_server_tpu.parallel.mesh import initialize_distributed

            initialize_distributed(
                coordinator_address=config.distributed_coordinator,
                num_processes=config.distributed_num_processes,
                process_id=config.distributed_process_id,
            )
            logger.info(
                "jax.distributed initialized",
                extra={"span_fields": {
                    "coordinator": config.distributed_coordinator,
                    "process_id": config.distributed_process_id,
                    "num_processes": config.distributed_num_processes,
                }},
            )

        # -- durable last-good state store (round 17, statestore.py) ------
        # Opened BEFORE any fetch/compile so the whole boot can lean on
        # it: the fsck pass quarantines torn/corrupt entries (never
        # fatal), the last-good manifest pins artifact digests for the
        # zero-network warm path, and the boot report below records how
        # warm this boot actually was.
        statestore = None
        boot_report: dict = {"warm": False}
        fingerprint = None
        pinned_artifacts: dict[str, str] = {}

        def _read_text(path) -> str | None:
            if not path:
                return None
            try:
                from pathlib import Path as _Path

                return _Path(path).read_text(encoding="utf-8")
            except OSError:
                return None

        if config.state_dir:
            from policy_server_tpu.statestore import (
                StateStore,
                compute_fingerprint,
            )

            statestore = StateStore(config.state_dir)
            fingerprint = compute_fingerprint({
                "policy_ids": sorted(config.policies),
                "backend": config.evaluation_backend,
                "predicate_opt": config.predicate_opt,
                "kernel": config.kernel,
                "columnar": config.columnar,
                "jax": _jax_version(),
            })
            manifest = statestore.last_good_manifest("default")
            boot_report.update(
                manifest_epoch=(
                    manifest.get("epoch") if manifest is not None else None
                ),
                manifest_found=manifest is not None,
                fingerprint_match=(
                    manifest is not None
                    and manifest.get("fingerprint") == fingerprint
                ),
            )
            # warm-boot artifact pins: tenants whose CURRENT policies
            # config is byte-identical to their last-good manifest load
            # those artifacts straight from the cache — zero network
            pinned_artifacts.update(
                statestore.pinned_digests(
                    "default", _read_text(config.policies_path)
                )
            )
            if config.tenants is not None:
                for t_name, t_spec in config.tenants.tenants.items():
                    pinned_artifacts.update(
                        statestore.pinned_digests(
                            t_name, _read_text(t_spec.policies_path)
                        )
                    )

        # offline sigstore trust root, loaded ONCE and shared by the
        # module resolver (artifact verification) and the evaluation
        # builder (wasm keyless v2/verify capability). The fetch/crypto
        # subsystem is optional — absent, keyless paths reject in-band
        # (lib.rs:309-336 analog; absent root = degraded like the
        # reference's failed TUF fetch, lib.rs:81-89).
        trust_root = None
        try:
            from policy_server_tpu.fetch.keyless import KeylessError, TrustRoot

            try:
                trust_root = TrustRoot.load_from_cache_dir(
                    config.sigstore_cache_dir
                )
            except KeylessError as e:
                # degrade like the reference's failed TUF fetch
                # (lib.rs:81-89): warn and continue without keyless —
                # verification configs that REQUIRE keyless will still
                # fail loudly per-requirement at policy bootstrap
                logger.warning(
                    "cannot load sigstore trust root; keyless "
                    "verification disabled: %s", e,
                )
        except ImportError:
            pass

        resolver = module_resolver
        if resolver is None and (config.sources or config.verification_config
                                 or _needs_fetch(config)):
            try:
                from policy_server_tpu.fetch import make_module_resolver
            except ImportError as e:
                raise RuntimeError(
                    "this configuration references non-builtin policy modules "
                    "or fetch settings, but the fetch subsystem is not "
                    "available"
                ) from e
            resolver = make_module_resolver(
                config,
                trust_root=trust_root,
                statestore=statestore,
                pinned_artifacts=pinned_artifacts,
            )

        context_service = _build_context_service(config)

        # registry client for the oci/v1/manifest_digest host capability:
        # the same token-auth/TLS/docker-config machinery registry://
        # pulls use (reference wires its registry sources into the
        # callback handler, src/lib.rs:91-125). Policies still opt in via
        # allowNetworkCapabilities before any egress happens.
        oci_digest_source = None
        try:
            from policy_server_tpu.fetch.downloader import Downloader

            oci_digest_source = Downloader(
                sources=config.sources,
                docker_config_json_path=config.docker_config_json_path,
            ).manifest_digest
        except ImportError:  # fetch subsystem unavailable: capability
            pass  # fails loudly in-band instead

        builder_kwargs = dict(
            module_resolver=resolver,
            always_accept_admission_reviews_on_namespace=(
                config.always_accept_admission_reviews_on_namespace
            ),
            context_service=context_service,
            # wasm guests get the configured wall-clock budget (the
            # epoch-interruption analog: fuel bounds instructions, this
            # bounds TIME, reference src/lib.rs:176-190)
            wasm_wall_clock_budget=config.policy_timeout,
            # offline sigstore trust root for the keyless v2/verify host
            # capability
            wasm_trust_root=trust_root,
            wasm_oci_digest_source=oci_digest_source,
            # bit-exact verdict cache / row dedup (0 disables)
            verdict_cache_size=config.verdict_cache_size,
            # device circuit breaker thresholds (one breaker per shard
            # environment; resilience.CircuitBreaker)
            breaker_config=dict(
                failure_threshold=config.breaker_failure_threshold,
                window_seconds=config.breaker_window_seconds,
                cooldown_seconds=config.breaker_cooldown_seconds,
            ),
            # columnar device transport + input-buffer donation (round 12)
            columnar=config.columnar,
            donate_buffers=config.donate_buffers,
            # predicate-program optimizer + device kernel form (round 15)
            predicate_opt=config.predicate_opt,
            kernel=config.kernel,
        )
        environment = _build_environment(config, builder_kwargs)

        # shadow recorder: the hot-reload canary's replay ring (every
        # epoch's batcher feeds the SAME ring, so a reload replays the
        # traffic the previous epoch actually served)
        reload_enabled = config.policy_reload_mode != "off"
        recorder = None
        if reload_enabled:
            from policy_server_tpu.lifecycle import ShadowRecorder

            recorder = ShadowRecorder(capacity=config.reload_canary_requests)

        # audit snapshot store: the background scanner's cluster view,
        # fed by every epoch's batcher (dirty-set tracking survives hot
        # reloads for the same reason the canary ring does)
        audit_enabled = config.audit_mode != "off"
        snapshot_store = None
        audit_resume: dict | None = None
        if audit_enabled:
            from policy_server_tpu.audit import SnapshotStore

            snapshot_store = SnapshotStore(
                max_bytes=config.audit_max_snapshot_bytes
            )
            if statestore is not None:
                # warm boot: rebuild the inventory from the audit spill
                # so the watch feed RESUMES from its spilled cursors
                # instead of re-LISTing the whole cluster (round 17)
                audit_resume = statestore.load_audit_spill()
                if audit_resume is not None:
                    restored = snapshot_store.restore_rows(
                        audit_resume["rows"]
                    )
                    boot_report["audit_rows_restored"] = restored
                    logger.info(
                        "audit snapshot restored from the state-store "
                        "spill", extra={"span_fields": {
                            "rows": restored,
                            "kinds_with_cursor": len(audit_resume["rvs"]),
                        }},
                    )
            if config.audit_resources_file:
                snapshot_store.seed_from_file(config.audit_resources_file)

        # persistent (object × policy) verdict matrix (round 23,
        # audit/matrix.py): built BEFORE the batchers (lookup admission
        # consults it on the submit paths) and restored AFTER the
        # snapshot (warm-boot cell validation hashes the restored rows).
        # Columns are keyed by policy-CONTENT fingerprint, so a stale
        # spilled policy set invalidates its columns by construction.
        verdict_matrix = None
        if audit_enabled and config.audit_matrix:
            from policy_server_tpu.audit import VerdictMatrix

            verdict_matrix = VerdictMatrix(
                snapshot=snapshot_store,
                statestore=statestore,
                spill_interval_seconds=config.audit_matrix_spill_seconds,
            )
            verdict_matrix.set_columns(config.policies or {}, 0)
            if statestore is not None:
                boot_report["matrix_cells_restored"] = (
                    verdict_matrix.restore()
                )

        # multi-tenant scaffolding (round 16, tenancy.py): the shared
        # weighted-fair dispatch scheduler and the default tenant's
        # admission quota exist BEFORE the default batcher is built so
        # the default tenant rides the same machinery as named tenants.
        # Without a manifest both stay None and every batcher below is
        # bit-identical to the single-tenant build.
        tenants_manifest = config.tenants
        fair_scheduler = None
        default_admission = None
        default_spec = None
        if tenants_manifest is not None:
            from policy_server_tpu.runtime.scheduler import (
                FairDispatchScheduler,
            )
            from policy_server_tpu.tenancy import (
                DEFAULT_TENANT,
                TenantAdmission,
            )

            default_spec = tenants_manifest.default
            weights = {
                name: spec.weight
                for name, spec in tenants_manifest.tenants.items()
            }
            weights[DEFAULT_TENANT] = default_spec.weight
            fair_scheduler = FairDispatchScheduler(
                max_concurrent=tenants_manifest.max_concurrent_dispatches,
                weights=weights,
            )
            if (
                default_spec.quota_rows_per_second > 0
                or default_spec.max_inflight > 0
            ):
                default_admission = TenantAdmission(
                    DEFAULT_TENANT,
                    rows_per_second=default_spec.quota_rows_per_second,
                    burst=default_spec.quota_burst,
                    max_inflight=default_spec.max_inflight,
                )

        import dataclasses

        def build_epoch_environment(policies):
            # defined BEFORE the batcher builders: the shard router
            # rebuilds sibling environments through it at boot, on every
            # reload epoch, and on rollback
            return _build_environment(
                dataclasses.replace(config, policies=dict(policies)),
                builder_kwargs,
            )

        from policy_server_tpu.supervision import SupervisorStats

        supervisor = SupervisorStats()

        from policy_server_tpu.runtime.shards import build_serving_shards

        def make_batcher(
            env, tenant_name, admission, spec, tenant_recorder, tracker
        ) -> MicroBatcher:
            """ONE batcher construction path for boot, every reload
            epoch, and every tenant — the knobs must not drift between
            generations. Per-tenant deadline class / degraded mode
            override the process defaults when the spec carries them."""
            request_timeout = config.request_timeout_ms
            degraded = config.degraded_mode
            if spec is not None:
                if spec.request_timeout_ms is not None:
                    request_timeout = spec.request_timeout_ms
                if spec.degraded_mode is not None:
                    degraded = spec.degraded_mode
            return MicroBatcher(
                env,
                max_batch_size=config.max_batch_size,
                batch_timeout_ms=config.batch_timeout_ms,
                policy_timeout=config.policy_timeout,
                queue_capacity=config.pool_size * config.max_batch_size,
                host_fastpath_threshold=config.host_fastpath_threshold,
                latency_budget_ms=config.latency_budget_ms,
                request_timeout_ms=request_timeout,
                degraded_mode=degraded,
                shadow_recorder=tenant_recorder,
                audit_tracker=tracker,
                # lookup admission stays scoped like the audit scanner:
                # only the DEFAULT tenant (the one feeding the snapshot
                # store) consults the matrix
                verdict_matrix=(
                    verdict_matrix if tracker is not None else None
                ),
                admission=admission,
                scheduler=fair_scheduler,
                tenant=tenant_name,
            )

        def build_batcher(env):
            """The default tenant's serving plane (also every reload
            epoch's, via the lifecycle manager): the plain MicroBatcher
            when --serving-shards is 1 (router BYPASS — the path is
            byte-identical to every previous round), else a ShardRouter
            over M full stacks whose sibling environments are rebuilt
            from env.source_policies. The tenant's admission quota and
            the fair scheduler are SHARED across its shards, so quotas
            compose instead of multiplying by M."""
            return build_serving_shards(
                env,
                lambda e: make_batcher(
                    e, "default", default_admission, default_spec,
                    recorder, snapshot_store,
                ),
                build_epoch_environment,
                config.serving_shards,
                heartbeat_seconds=config.shard_heartbeat_seconds,
                supervisor=supervisor,
                statestore=statestore,
            )

        batcher = build_batcher(environment)
        if config.warmup_at_boot and config.evaluation_backend == "jax":
            batcher.warmup()
        batcher.start()

        state = ApiServerState(
            evaluation_environment=environment,
            batcher=batcher,
            hostname=config.hostname,
            enable_pprof=config.enable_pprof,
            ready=not reload_enabled,  # lifecycle flips it below
            admin_token=config.reload_admin_token,
            statestore=statestore,
            boot_report=boot_report,
            supervisor=supervisor,
            audit_matrix=verdict_matrix,
            audit_stream_max_clients=config.audit_stream_max_clients,
        )

        def build_oracle_environment(policies):
            # the canary referee: the host-oracle backend over the
            # SAME candidate set, sharing the boot module resolver
            oracle_builder = EvaluationEnvironmentBuilder(
                backend="oracle",
                continue_on_errors=config.continue_on_errors,
                **builder_kwargs,
            )
            return oracle_builder.build(dict(policies))

        if reload_enabled:
            from policy_server_tpu.lifecycle import PolicyLifecycleManager

            read_policies = None
            if config.policies_path:
                path = config.policies_path

                def read_policies():
                    # (policies, yaml_text): the manifest must persist
                    # the exact bytes this reload parsed, never a later
                    # re-read (config/config.read_policies_source)
                    from policy_server_tpu.config.config import (
                        read_policies_source,
                    )

                    return read_policies_source(path)

            state.lifecycle = PolicyLifecycleManager(
                state=state,
                build_environment=build_epoch_environment,
                build_oracle_environment=build_oracle_environment,
                build_batcher=build_batcher,
                recorder=recorder,
                read_policies=read_policies,
                policies_path=config.policies_path,
                mode=config.policy_reload_mode,
                canary_requests=config.reload_canary_requests,
                divergence_threshold=config.reload_divergence_threshold,
                warmup=(
                    config.warmup_at_boot
                    and config.evaluation_backend == "jax"
                ),
                statestore=statestore,
                fingerprint=fingerprint,
            )
            # first epoch = the boot build; flips state.ready (readiness
            # honesty: compiled + warmed before the probe says 200). The
            # yaml text is the same read the warm-boot pin decision used.
            state.lifecycle.install_first_epoch(
                environment, batcher, config.policies,
                policies_yaml=_read_text(config.policies_path),
            )
            state.lifecycle.start_watching()

        if audit_enabled:
            from policy_server_tpu.audit import (
                AuditScanner,
                PolicyReportStore,
            )

            state.audit = AuditScanner(
                state=state,
                snapshot=snapshot_store,
                reports=PolicyReportStore(),
                mode=config.audit_mode,
                interval_seconds=config.audit_interval_seconds,
                batch_size=config.audit_batch_size,
                matrix=verdict_matrix,
            )
            if boot_report.get("matrix_cells_restored", 0) > 0:
                # warm matrix resume: the restore proved the covered
                # rows current under the serving column fingerprints, so
                # the boot pass is a DIRTY sweep of the remainder — not
                # a whole-cluster re-judge
                state.audit.skip_boot_full_sweep()
            if state.lifecycle is not None:
                # epoch coherence: a promotion re-judges everything under
                # the new set; a rollback stales the revoked epoch's rows
                state.lifecycle.set_epoch_hooks(
                    on_promote=state.audit.on_promote,
                    on_rollback=state.audit.on_rollback,
                )
                if config.audit_matrix_whatif and verdict_matrix is not None:
                    # cluster what-if (round 23, stretch): during the
                    # shadow canary, evaluate the CANDIDATE's changed
                    # columns against the live snapshot and keep the
                    # verdict-flip diff for the reload-status surface
                    state.lifecycle.set_whatif_matrix(verdict_matrix)
            if config.audit_watch:
                # live-cluster feed: list+watch events populate the
                # snapshot store the scanner sweeps, so the audited
                # inventory tracks the cluster instead of only webhook
                # traffic (audit/watch_feed.py)
                state.audit_watch = _build_audit_watch_feed(
                    config, snapshot_store,
                    statestore=statestore, resume=audit_resume,
                )
                state.audit.watch_feed = state.audit_watch
            state.audit.start()

        if tenants_manifest is not None:
            # -- named tenants (round 16, tenancy.py): one full epoch
            # stack per tenant — own environment (verdict cache +
            # breaker), own batcher (admission quota, deadline class,
            # degraded mode), own lifecycle (reload/canary/rollback +
            # digest watch on ITS policies file). All tenants' policy
            # sets lower over the same device fleet/mesh; the fair
            # scheduler time-shares dispatch slots between them. The
            # audit scanner stays scoped to the DEFAULT tenant: named
            # tenants' traffic never feeds its snapshot store.
            from policy_server_tpu import failpoints
            from policy_server_tpu.lifecycle import (
                PolicyLifecycleManager,
                ShadowRecorder,
            )
            from policy_server_tpu.tenancy import (
                Tenant,
                TenantAdmission,
                TenantManager,
                TenantState,
            )

            manager = TenantManager(scheduler=fair_scheduler)
            manager.add(
                Tenant(DEFAULT_TENANT, default_spec, state,
                       default_admission)
            )

            def read_tenant_boot_policies(name: str, spec):
                """One tenant's boot-time ``(policies, yaml_text)`` read,
                carrying the crash-tolerance contract: the
                ``tenant.reload`` chaos site fires here too (an
                unreadable manifest at BOOT is the same failure as one
                at reload), and with a state store the read degrades
                LOUDLY to the tenant's last-good manifest bytes instead
                of fail-closing the whole boot."""
                import yaml as _yaml

                from policy_server_tpu.config.config import (
                    read_policies_source,
                )
                from policy_server_tpu.models.policy import parse_policies

                try:
                    with failpoints.scope(name):
                        failpoints.fire("tenant.reload")
                    return read_policies_source(spec.policies_path)
                except Exception as e:  # noqa: BLE001 — every read
                    # failure takes the same last-good path
                    if statestore is not None:
                        m = statestore.last_good_manifest(name)
                        if m is not None and m.get("policies_yaml"):
                            statestore.count_degraded_load()
                            logger.error(
                                "tenant %s policies read FAILED (%s); "
                                "booting DEGRADED on the last-good "
                                "manifest (epoch %s) — fix the manifest "
                                "and reload to clear this",
                                name, e, m.get("epoch"),
                            )
                            return (
                                parse_policies(
                                    _yaml.safe_load(m["policies_yaml"])
                                ),
                                m["policies_yaml"],
                            )
                    raise

            for tenant_name, spec in tenants_manifest.tenants.items():
                t_policies, t_policies_yaml = read_tenant_boot_policies(
                    tenant_name, spec
                )
                t_admission = None
                if spec.quota_rows_per_second > 0 or spec.max_inflight > 0:
                    t_admission = TenantAdmission(
                        tenant_name,
                        rows_per_second=spec.quota_rows_per_second,
                        burst=spec.quota_burst,
                        max_inflight=spec.max_inflight,
                    )
                t_recorder = (
                    ShadowRecorder(capacity=config.reload_canary_requests)
                    if reload_enabled else None
                )
                t_env = build_epoch_environment(t_policies)
                t_state = TenantState(name=tenant_name)

                def t_build_batcher(
                    env, _n=tenant_name, _a=t_admission, _s=spec,
                    _r=t_recorder,
                ):
                    # per-tenant shard set (round 22): the tenant's
                    # admission quota and the process-wide fair
                    # scheduler are SHARED across its shards, so tenant
                    # fairness and in-flight caps compose across the
                    # set instead of multiplying by M
                    return build_serving_shards(
                        env,
                        lambda e: make_batcher(e, _n, _a, _s, _r, None),
                        build_epoch_environment,
                        config.serving_shards,
                        heartbeat_seconds=config.shard_heartbeat_seconds,
                        supervisor=supervisor,
                        statestore=statestore,
                    )

                def t_read_policies(_spec=spec):
                    # the tenant.reload chaos site: an armed fault here
                    # rejects THIS tenant's reload at the fetch stage
                    # (last-good keeps serving); other tenants' pipelines
                    # are untouched. Returns (policies, yaml_text) so the
                    # manifest persists what this reload actually parsed.
                    from policy_server_tpu.config.config import (
                        read_policies_source,
                    )

                    failpoints.fire("tenant.reload")
                    return read_policies_source(_spec.policies_path)

                t_batcher = t_build_batcher(t_env)
                if config.warmup_at_boot and config.evaluation_backend == "jax":
                    t_batcher.warmup()
                t_batcher.start()
                if reload_enabled:
                    t_state.lifecycle = PolicyLifecycleManager(
                        state=t_state,
                        build_environment=build_epoch_environment,
                        build_oracle_environment=build_oracle_environment,
                        build_batcher=t_build_batcher,
                        recorder=t_recorder,
                        read_policies=t_read_policies,
                        policies_path=spec.policies_path,
                        mode=config.policy_reload_mode,
                        canary_requests=config.reload_canary_requests,
                        divergence_threshold=(
                            config.reload_divergence_threshold
                        ),
                        warmup=(
                            config.warmup_at_boot
                            and config.evaluation_backend == "jax"
                        ),
                        tenant=tenant_name,
                        statestore=statestore,
                        fingerprint=fingerprint,
                    )
                    t_state.lifecycle.install_first_epoch(
                        t_env, t_batcher, t_policies,
                        policies_yaml=t_policies_yaml,
                    )
                    t_state.lifecycle.start_watching()
                else:
                    t_state.evaluation_environment = t_env
                    t_state.batcher = t_batcher
                    t_state.ready = True
                manager.add(
                    Tenant(tenant_name, spec, t_state, t_admission)
                )
                logger.info(
                    "tenant serving", extra={"span_fields": {
                        "tenant": tenant_name,
                        "policies": len(t_policies),
                        "weight": spec.weight,
                        "quota_rows_per_second": spec.quota_rows_per_second,
                    }},
                )
            state.tenants = manager

        def runtime_stats():
            # one locked snapshot per scrape: bare attribute reads from
            # here would be the cross-module dirty reads the batcher's
            # guarded-by annotations forbid. Read through STATE, not the
            # bootstrap locals: a hot reload rebinds the epoch pointer,
            # and the scrape must follow the serving epoch.
            batcher = state.batcher
            environment = state.evaluation_environment
            bstats = batcher.stats_snapshot()
            yield (
                metrics_names.BATCHES_DISPATCHED, "counter",
                "Micro-batches dispatched to the device",
                bstats["batches_dispatched"],
            )
            yield (
                metrics_names.REQUESTS_DISPATCHED, "counter",
                "Requests dispatched through the micro-batcher",
                bstats["requests_dispatched"],
            )
            yield (
                metrics_names.DEADLINE_ABANDONED_BATCHES, "counter",
                "Device batches abandoned by the dispatch watchdog",
                bstats["deadline_abandoned_batches"],
            )
            yield (
                metrics_names.QUEUE_DEPTH, "gauge",
                "Requests waiting for batch formation",
                batcher.queue_depth(),
            )
            yield (
                metrics_names.ORACLE_FALLBACKS, "counter",
                "Requests routed to the host oracle (schema overflow)",
                getattr(environment, "oracle_fallbacks", 0) or 0,
            )
            yield (
                metrics_names.HOST_FASTPATH_BATCHES, "counter",
                "Micro-batches answered by the host latency fast-path",
                bstats["host_fastpath_batches"],
            )
            yield (
                metrics_names.HOST_FASTPATH_REQUESTS, "counter",
                "Requests answered by the host latency fast-path",
                getattr(environment, "host_fastpath_requests", 0) or 0,
            )
            yield (
                metrics_names.BUDGET_ROUTED_BATCHES, "counter",
                "Batches routed host-side by the latency-budget check",
                bstats["budget_routed_batches"],
            )
            # Two-tier dedup + verdict cache (round 6): hit rate is the
            # cache's whole value proposition, so it must be visible on a
            # running server (VERDICT r5 weak #4)
            dedup = getattr(environment, "dedup_stats", None) or {}
            yield (
                metrics_names.DEDUP_BLOB_HITS, "counter",
                "Pre-encode blob-tier dedup hits (exact payload replays "
                "that skipped encoding)",
                dedup.get("blob_cache_hits", 0),
            )
            yield (
                metrics_names.DEDUP_BLOB_MISSES, "counter",
                "Pre-encode blob-tier dedup misses",
                dedup.get("blob_cache_misses", 0),
            )
            yield (
                metrics_names.VERDICT_CACHE_HITS, "counter",
                "Row-tier verdict cache hits (post-encode, "
                "uid-insensitive)",
                dedup.get("cache_hits", 0),
            )
            yield (
                metrics_names.VERDICT_CACHE_MISSES, "counter",
                "Row-tier verdict cache misses",
                dedup.get("cache_misses", 0),
            )
            yield (
                metrics_names.VERDICT_CACHE_BYTES, "gauge",
                "Resident bytes across both verdict-cache tiers",
                dedup.get("cache_bytes", 0) + dedup.get("blob_cache_bytes", 0),
            )
            yield (
                metrics_names.BATCH_DEDUP_HITS, "counter",
                "Rows answered by an identical row in the same batch",
                dedup.get("batch_dup_hits", 0),
            )
            yield (
                metrics_names.FRAGMENT_HITS, "counter",
                "Cache-hit rows answered as pre-serialized response "
                "fragments (zero per-row materialization)",
                dedup.get("fragment_hits", 0),
            )
            # Host-pipeline decomposition (PROFILE.md round 6): where the
            # per-row host time goes on the native dispatch path
            profile = getattr(environment, "host_profile", None) or {}
            yield (
                metrics_names.HOST_ENCODE_SECONDS, "counter",
                "Host time in payload-blob build + native batch encode",
                profile.get("encode_ns", 0) / 1e9,
            )
            yield (
                metrics_names.HOST_ENCODE_ROWS, "counter",
                "Rows through the native encoder (blob-tier hits skip it)",
                profile.get("encode_rows", 0),
            )
            yield (
                metrics_names.HOST_BOOKKEEPING_SECONDS, "counter",
                "Host time in dedup tiers + slot/LRU bookkeeping",
                profile.get("bookkeeping_ns", 0) / 1e9,
            )
            yield (
                metrics_names.DISPATCH_WAIT_SECONDS, "counter",
                "Host time blocked on device results",
                profile.get("dispatch_wait_ns", 0) / 1e9,
            )
            yield (
                metrics_names.DISPATCHED_ROWS, "counter",
                "Unique rows actually shipped to the device",
                profile.get("dispatched_rows", 0),
            )
            # Resilience surface (round 7): shedding, deadline drops,
            # breaker state/transitions, degraded answers, fetch retries
            yield (
                metrics_names.SHED_REQUESTS, "counter",
                "Requests shed at admission (429 + Retry-After)",
                bstats["shed_requests"],
            )
            yield (
                metrics_names.EXPIRED_DROPPED, "counter",
                "Expired rows dropped before encode/dispatch (no dead "
                "work)",
                bstats["expired_dropped"],
            )
            yield (
                metrics_names.DEGRADED_RESPONSES, "counter",
                "Requests answered by the --degraded-mode policy while "
                "the device breaker was fully tripped",
                bstats["degraded_responses"],
            )
            breaker = getattr(environment, "breaker_stats", None) or {}
            yield (
                metrics_names.BREAKER_OPEN_SHARDS, "gauge",
                "Device shards whose circuit breaker is currently "
                "tripped (open or half-open)",
                breaker.get("open_shards", 0),
            )
            yield (
                metrics_names.BREAKER_TRIPS, "counter",
                "Circuit breaker CLOSED/HALF_OPEN -> OPEN transitions",
                breaker.get("trips", 0),
            )
            yield (
                metrics_names.BREAKER_RECOVERIES, "counter",
                "Circuit breaker HALF_OPEN -> CLOSED recoveries",
                breaker.get("recoveries", 0),
            )
            yield (
                metrics_names.BREAKER_PROBES, "counter",
                "Half-open recovery probe dispatches admitted",
                breaker.get("probes", 0),
            )
            yield (
                metrics_names.BREAKER_SHORT_CIRCUITED, "counter",
                "Requests served host-side because a breaker was open",
                breaker.get("short_circuited_requests", 0),
            )
            try:
                from policy_server_tpu.fetch.downloader import retry_stats

                fetch_retries = retry_stats()
            except ImportError:  # fetch subsystem unavailable
                fetch_retries = {}
            yield (
                metrics_names.FETCH_RETRY_ATTEMPTS, "counter",
                "Transient policy-fetch failures retried with backoff",
                fetch_retries.get("attempts", 0),
            )
            yield (
                metrics_names.FETCH_RETRY_GIVEUPS, "counter",
                "Policy-fetch operations that exhausted the retry budget",
                fetch_retries.get("giveups", 0),
            )
            # Policy-lifecycle surface (round 9): hot-reload promotions,
            # rejected candidates, rollbacks, canary volume, and the
            # serving epoch — a bad policy push must be LOUD on the
            # dashboard even though last-good kept serving
            lstats = (
                state.lifecycle.stats() if state.lifecycle is not None
                else {}
            )
            yield (
                metrics_names.POLICY_RELOADS, "counter",
                "Policy hot-reload promotions (new epoch serving)",
                lstats.get("reloads", 0),
            )
            yield (
                metrics_names.POLICY_RELOAD_FAILURES, "counter",
                "Policy reload candidates rejected (fetch/compile/canary "
                "failure) — last-good kept serving",
                lstats.get("reload_failures", 0),
            )
            yield (
                metrics_names.POLICY_RELOAD_ROLLBACKS, "counter",
                "Reverts to the last-good policy set: rejected canaries "
                "plus explicit POST /policies/rollback",
                lstats.get("rollbacks", 0),
            )
            yield (
                metrics_names.RELOAD_CANARY_REPLAYS, "counter",
                "Recorded/synthetic requests replayed through candidate "
                "epochs during shadow canary",
                lstats.get("canary_replays", 0),
            )
            yield (
                metrics_names.RELOAD_CANARY_DIVERGENCES, "counter",
                "Canary replays whose candidate verdict diverged from "
                "the host oracle",
                lstats.get("canary_divergences", 0),
            )
            yield (
                metrics_names.POLICY_EPOCH, "gauge",
                "Monotonic number of the currently serving policy epoch "
                "(0 = the boot set)",
                lstats.get("epoch", 0),
            )
            # Background audit scanner (round 10): lane throughput and
            # preemptions from the batcher, sweep cadence / report
            # freshness / snapshot footprint from the scanner. All zero
            # with --audit-mode off (the families still export so the
            # dashboard panels resolve on every deployment).
            yield (
                metrics_names.AUDIT_BATCHES_DISPATCHED, "counter",
                "Best-effort audit-lane batches dispatched on idle slots",
                bstats["audit_batches_dispatched"],
            )
            yield (
                metrics_names.AUDIT_PREEMPTIONS, "counter",
                "Audit batches re-queued because live work arrived first",
                bstats["audit_preemptions"],
            )
            yield (
                metrics_names.AUDIT_LANE_DEPTH, "gauge",
                "Audit batches waiting for an idle dispatch slot",
                batcher.audit_lane_depth(),
            )
            astats = state.audit.stats() if state.audit is not None else {}
            yield (
                metrics_names.AUDIT_ROWS_SCANNED, "counter",
                "Resource x policy rows the audit scanner has judged",
                astats.get("rows_scanned", 0),
            )
            yield (
                metrics_names.AUDIT_FULL_SWEEPS, "counter",
                "Completed full audit sweeps (boot, epoch promotions, "
                "rollbacks)",
                astats.get("full_sweeps", 0),
            )
            yield (
                metrics_names.AUDIT_DIRTY_SWEEPS, "counter",
                "Completed dirty-set audit sweeps (interval cadence)",
                astats.get("dirty_sweeps", 0),
            )
            yield (
                metrics_names.AUDIT_SWEEP_ERRORS, "counter",
                "Audit sweeps aborted by a fault (retried on the next "
                "trigger)",
                astats.get("sweep_errors", 0),
            )
            yield (
                metrics_names.AUDIT_PAUSED_SWEEPS, "counter",
                "Audit sweeps skipped while the device breaker was open",
                astats.get("paused_sweeps", 0),
            )
            yield (
                metrics_names.AUDIT_REPORT_FRESHNESS, "gauge",
                "Seconds since the last completed full audit sweep "
                "(-1 before the first)",
                astats.get("freshness_seconds", -1.0),
            )
            yield (
                metrics_names.AUDIT_REPORTS_RESIDENT, "gauge",
                "Audit report rows currently held",
                astats.get("reports_resident", 0),
            )
            yield (
                metrics_names.AUDIT_REPORTS_STALE, "gauge",
                "Audit report rows stamped by a rolled-back policy epoch",
                astats.get("reports_stale", 0),
            )
            yield (
                metrics_names.AUDIT_SNAPSHOT_RESOURCES, "gauge",
                "Cluster resources held in the audit snapshot store",
                astats.get("snapshot_resources", 0),
            )
            yield (
                metrics_names.AUDIT_SNAPSHOT_BYTES, "gauge",
                "Resident bytes of the audit snapshot store",
                astats.get("snapshot_bytes", 0),
            )
            # Native HTTP front-end (round 11): framing throughput, parse
            # fallbacks (Python stays the parse oracle), serialization
            # split, and the framing/queue legs of the per-stage time
            # decomposition. All zero with --frontend python (families
            # still export so the dashboard panels resolve everywhere).
            nstats = (
                state.native_frontend.stats()
                if state.native_frontend is not None
                else {}
            )
            yield (
                metrics_names.NATIVE_HTTP_REQUESTS, "counter",
                "HTTP requests framed by the native (GIL-free C++) "
                "front-end",
                nstats.get("http_requests", 0),
            )
            yield (
                metrics_names.NATIVE_PARSE_FALLBACKS, "counter",
                "Requests the native AdmissionReview parser declined and "
                "shipped to the Python parse oracle (floats, duplicate "
                "keys, malformed bodies)",
                nstats.get("parse_fallbacks", 0),
            )
            yield (
                metrics_names.NATIVE_RING_FULL, "counter",
                "Requests answered 503 because the native submission "
                "ring was full (drainer overrun)",
                nstats.get("ring_full_rejections", 0),
            )
            yield (
                metrics_names.NATIVE_VERDICTS_SERIALIZED, "counter",
                "Responses serialized natively (common verdict shape)",
                nstats.get("responses_native_serialized", 0),
            )
            yield (
                metrics_names.NATIVE_PYTHON_SERIALIZED, "counter",
                "Responses rendered by Python behind the native frontend "
                "(errors, mutations, exotic status fields)",
                nstats.get("responses_python_serialized", 0),
            )
            yield (
                metrics_names.NATIVE_FRAMING_SECONDS, "counter",
                "Native-thread time in HTTP framing, AdmissionReview "
                "canonicalization, and response serialization",
                nstats.get("framing_ns", 0) / 1e9,
            )
            yield (
                metrics_names.NATIVE_INFLIGHT, "gauge",
                "Requests accepted by the native frontend still awaiting "
                "their completion",
                nstats.get("inflight", 0),
            )
            yield (
                metrics_names.QUEUE_WAIT_SECONDS, "counter",
                "Cumulative time requests spent queued between batcher "
                "submission and batch formation",
                bstats["queue_wait_ns"] / 1e9,
            )
            # Array-at-a-time serving path + columnar transport (round
            # 12): bulk admission volume, wire bytes vs the row-packed
            # equivalent, delta-column hit rate, donation, and the
            # device-resident zero-constant footprint. All zero with
            # --columnar off / the python submission paths (families
            # still export so dashboard panels resolve everywhere).
            yield (
                metrics_names.BULK_SUBMITS, "counter",
                "submit_many bursts admitted (one queue-lock "
                "acquisition each)",
                bstats["bulk_submits"],
            )
            yield (
                metrics_names.BULK_SUBMITTED_ROWS, "counter",
                "Rows admitted through submit_many bursts",
                bstats["bulk_submitted_rows"],
            )
            yield (
                metrics_names.WIRE_BYTES_SHIPPED, "counter",
                "Bytes actually shipped to the device by the columnar "
                "transport (delta planes + column indices)",
                profile.get("wire_bytes_shipped", 0),
            )
            yield (
                metrics_names.WIRE_BYTES_PACKED_EQUIV, "counter",
                "Bytes the row-packed transport form would have shipped "
                "for the same dispatches",
                profile.get("wire_bytes_packed_equiv", 0),
            )
            yield (
                metrics_names.WIRE_ROWS, "counter",
                "Rows shipped by the columnar transport (bytes/row = "
                "wire_bytes_shipped / this)",
                profile.get("wire_rows", 0),
            )
            yield (
                metrics_names.DELTA_COLS_SHIPPED, "counter",
                "32-bit feature columns shipped (delta columns with any "
                "nonzero value, after power-of-two padding)",
                profile.get("delta_cols_shipped", 0),
            )
            yield (
                metrics_names.DELTA_COLS_TOTAL, "counter",
                "32-bit feature columns in the dispatched schemas (hit "
                "rate = 1 - shipped/total)",
                profile.get("delta_cols_total", 0),
            )
            yield (
                metrics_names.DONATED_DISPATCHES, "counter",
                "Columnar dispatches whose input buffers were donated "
                "(jax donate_argnums)",
                profile.get("donated_dispatches", 0),
            )
            yield (
                metrics_names.RESIDENT_CONST_BYTES, "counter",
                "Bytes of elided zero planes/columns materialized as "
                "device-resident constants of compiled columnar programs",
                profile.get("resident_const_bytes", 0),
            )
            # Live watch feed + connection-abuse hardening + soak-window
            # SLOs (round 13). All zero without --audit-watch / the
            # native frontend / a running soak (families still export so
            # dashboard panels resolve everywhere).
            yield (
                metrics_names.WATCH_EVENTS_APPLIED, "counter",
                "Kubernetes watch events applied to the audit snapshot "
                "store (ADDED/MODIFIED supersede, DELETED evicts)",
                astats.get("watch_events_applied", 0),
            )
            yield (
                metrics_names.WATCH_EVENTS_DROPPED, "counter",
                "Watch events dropped by the bounded feed queue (each "
                "forces a counted full re-LIST resync of its kind)",
                astats.get("watch_events_dropped", 0),
            )
            yield (
                metrics_names.WATCH_RESYNCS, "counter",
                "Full re-LIST resyncs of the audit watch feed (410 "
                "expiry, transport fault, queue overflow, or the "
                "staleness-bounding interval)",
                astats.get("watch_resyncs", 0),
            )
            yield (
                metrics_names.NATIVE_IDLE_CLOSES, "counter",
                "Native-frontend connections reaped by the idle or "
                "read (slowloris) timeout",
                nstats.get("idle_timeout_closes", 0),
            )
            yield (
                metrics_names.NATIVE_CONN_CAP_REJECTS, "counter",
                "Connections answered an in-band 503 because the "
                "native frontend's connection cap was reached",
                nstats.get("conn_cap_rejections", 0),
            )
            # Native TLS termination (round 20). The expiry gauge and
            # reload counters follow certs.py through the state, so
            # they export under the aiohttp TLS fallback too; the
            # handshake counters come from the native loops and are
            # zero under aiohttp termination or plaintext (families
            # still export so dashboard panels resolve everywhere).
            _reloadable = getattr(state, "tls_reloadable", None)
            _tlsmgr = getattr(state, "native_tls", None)
            _expiry = (
                _reloadable.identity_not_after()
                if _reloadable is not None
                else None
            )
            _tls_reloads, _tls_reload_failures = (
                _reloadable.counters()
                if _reloadable is not None
                else (0, 0)
            )
            yield (
                metrics_names.TLS_CERT_EXPIRY_SECONDS, "gauge",
                "Seconds until the serving TLS identity's notAfter "
                "(negative = expired; 0 when TLS is off or the leaf "
                "is undecodable)",
                (_expiry - _time.time()) if _expiry is not None else 0,
            )
            yield (
                metrics_names.TLS_HANDSHAKES_OK, "counter",
                "TLS handshakes completed by the native frontend",
                nstats.get("tls_handshakes_ok", 0),
            )
            yield (
                metrics_names.TLS_HANDSHAKES_FAILED, "counter",
                "Native TLS handshakes that failed hard (bad record, "
                "mTLS client-CA rejection, injected tls.handshake "
                "faults)",
                nstats.get("tls_handshakes_failed", 0),
            )
            yield (
                metrics_names.TLS_HANDSHAKE_TIMEOUTS, "counter",
                "Native TLS handshakes reaped by the arrival timeout "
                "(byte drips never refresh it — the TLS-layer "
                "slowloris defense)",
                nstats.get("tls_handshake_timeouts", 0),
            )
            yield (
                metrics_names.TLS_HANDSHAKE_DISCONNECTS, "counter",
                "Connections that disconnected mid-handshake before "
                "the native TLS handshake completed",
                nstats.get("tls_handshake_disconnects", 0),
            )
            yield (
                metrics_names.TLS_CLEAN_CLOSES, "counter",
                "Native TLS connections closed with a close_notify "
                "alert (in-band rejections included — no "
                "truncation-looking RSTs for well-behaved clients)",
                nstats.get("tls_clean_closes", 0),
            )
            yield (
                metrics_names.TLS_GENERATIONS, "counter",
                "SSL_CTX generations installed on the native loops "
                "(boot + each successful hot-rotation; established "
                "connections drain on the generation they pinned)",
                _tlsmgr.snapshot()["generations"] if _tlsmgr else 0,
            )
            yield (
                metrics_names.TLS_RELOADS, "counter",
                "TLS identity/client-CA hot reloads applied by "
                "certs.py (SIGHUP or digest-watch rotation)",
                _tls_reloads,
            )
            yield (
                metrics_names.TLS_RELOAD_FAILURES, "counter",
                "TLS reload attempts that failed validation; the "
                "last-good identity kept serving each time",
                _tls_reload_failures,
            )
            yield (
                metrics_names.TLS_NATIVE_TERMINATION, "gauge",
                "1 when TLS terminates on the native epoll loops, 0 "
                "under the aiohttp terminator or plaintext",
                1 if _tlsmgr is not None else 0,
            )
            # Predicate-program optimizer + Pallas kernel path (round
            # 15). Optimizer facts are static per serving epoch (the
            # pass re-runs for every reload candidate); gauges follow
            # the epoch pointer. All zero with --predicate-opt off /
            # --kernel xla (families still export so dashboard panels
            # resolve everywhere).
            ostats = getattr(environment, "optimizer_stats", None) or {}
            pstats = getattr(environment, "pallas_stats", None) or {}
            yield (
                metrics_names.PREDICATE_SUBTREES_SHARED, "gauge",
                "Distinct predicate subtrees shared across policies by "
                "the optimizer's CSE table (computed once per program "
                "instead of once per policy)",
                ostats.get("subtrees_shared", 0),
            )
            yield (
                metrics_names.PREDICATE_POLICIES_FOLDED, "gauge",
                "Policies whose verdict folded to a constant and "
                "dropped out of the device program",
                ostats.get("policies_folded", 0),
            )
            yield (
                metrics_names.PREDICATE_RULES_FOLDED, "gauge",
                "Rule conditions folded to constants (unreachable or "
                "constant rules; indices preserved)",
                ostats.get("rules_folded", 0),
            )
            yield (
                metrics_names.PREDICATE_FIELDS_PRUNED, "gauge",
                "Feature-schema fields pruned by the optimizer (dead "
                "gather columns + zero-fill-redundant validity masks)",
                ostats.get("fields_pruned", 0),
            )
            yield (
                metrics_names.PREDICATE_ROW_BYTES_SAVED, "gauge",
                "Packed-row bytes saved per row, summed over schema "
                "buckets, vs the unoptimized layout",
                ostats.get("row_bytes_saved", 0),
            )
            yield (
                metrics_names.PALLAS_DISPATCHES, "counter",
                "Device dispatches served by the fused Pallas "
                "gather→predicate→reduce kernel (--kernel pallas, hot "
                "buckets)",
                pstats.get("dispatches", 0),
            )
            yield (
                metrics_names.PALLAS_BUCKETS_ARMED, "gauge",
                "Schema buckets currently armed for the Pallas kernel "
                "(per-bucket opt-in by dispatch count)",
                pstats.get("buckets_armed", 0),
            )
            yield (
                metrics_names.PALLAS_INTERPRET_MODE, "gauge",
                "1 when the Pallas kernel runs in interpret mode (the "
                "Mosaic capability probe failed — bit-exact, slow, "
                "loudly warned)",
                pstats.get("interpret_mode", 0),
            )
            # Multi-tenant serving (round 16): tenant-labelled
            # admission / fair-dispatch / lifecycle families. Sample
            # lists are empty without a --tenants manifest (the families
            # still export so dashboard panels resolve everywhere).
            tmgr = state.tenants
            tstats = tmgr.stats() if tmgr is not None else {}
            yield (
                metrics_names.TENANT_SHED_ROWS, "counter",
                "Rows shed by a tenant's admission quota (token bucket "
                "+ in-flight cap; 429 + Retry-After)",
                tstats.get("shed_rows", []), ("tenant",),
            )
            yield (
                metrics_names.TENANT_ADMITTED_ROWS, "counter",
                "Rows admitted through a tenant's admission quota",
                tstats.get("admitted_rows", []), ("tenant",),
            )
            yield (
                metrics_names.TENANT_INFLIGHT_ROWS, "gauge",
                "Admitted-but-unresolved rows per tenant (the "
                "max-inflight cap's numerator)",
                tstats.get("inflight_rows", []), ("tenant",),
            )
            yield (
                metrics_names.TENANT_QUEUE_DEPTH, "gauge",
                "Requests waiting in each tenant batcher's submission "
                "queue",
                tstats.get("queue_depth", []), ("tenant",),
            )
            yield (
                metrics_names.TENANT_DISPATCH_GRANTS, "counter",
                "Weighted-fair dispatch slots granted per tenant "
                "(live + audit classes)",
                tstats.get("dispatch_grants", []), ("tenant",),
            )
            yield (
                metrics_names.TENANT_DISPATCH_WAIT_SECONDS, "counter",
                "Cumulative time each tenant's batches waited for a "
                "fair-scheduler dispatch slot",
                tstats.get("dispatch_wait_seconds", []), ("tenant",),
            )
            yield (
                metrics_names.TENANT_EPOCH, "gauge",
                "Each tenant's currently serving policy epoch",
                tstats.get("epoch", []), ("tenant",),
            )
            yield (
                metrics_names.TENANT_ROLLBACKS, "counter",
                "Per-tenant reverts to last-good (rejected canaries + "
                "explicit rollbacks)",
                tstats.get("rollbacks", []), ("tenant",),
            )
            yield (
                metrics_names.TENANT_READY, "gauge",
                "Per-tenant honest readiness (1 ready, 0 degraded — "
                "the /readiness/{tenant} verdict)",
                tstats.get("ready", []), ("tenant",),
            )
            yield (
                metrics_names.TENANTS_SERVING, "gauge",
                "Tenants served by this process (0 without a tenants "
                "manifest; includes the default tenant otherwise)",
                tstats.get("serving", 0),
            )
            soak = getattr(state, "soak", None) or {}
            yield (
                metrics_names.SOAK_WINDOW_RPS, "gauge",
                "Requests/s of the current soak window (tools/soak "
                "in-process engine; 0 outside a soak)",
                soak.get("rps", 0.0),
            )
            yield (
                metrics_names.SOAK_WINDOW_P99_MS, "gauge",
                "p99 latency (ms) of the current soak window",
                soak.get("p99_ms", 0.0),
            )
            yield (
                metrics_names.SOAK_WINDOW_SHED_RATE, "gauge",
                "Shed (429) fraction of the current soak window",
                soak.get("shed_rate", 0.0),
            )
            # Crash-tolerant serving (round 17): boot shape, the durable
            # state store's cache/journal/fsck accounting, and the
            # supervision counters (worker respawn breaker + self-heal
            # watchdog). All zero without --state-dir / prefork workers
            # (families still export so dashboard panels resolve
            # everywhere).
            boot = getattr(state, "boot_report", None) or {}
            yield (
                metrics_names.BOOT_TIME_TO_READY, "gauge",
                "Seconds from process bootstrap start to the first "
                "serving epoch compiled+warmed (the MTTR numerator)",
                boot.get("time_to_ready_seconds", 0.0),
            )
            yield (
                metrics_names.BOOT_WARM, "gauge",
                "1 when this boot was WARM: a last-good manifest was "
                "found in the state store (artifact pins / audit resume "
                "applied where eligible)",
                1 if boot.get("warm") else 0,
            )
            yield (
                metrics_names.BOOT_DEGRADED_SOURCES, "gauge",
                "Policy sources this boot served from last-good state "
                "because the live read/fetch FAILED (loud degradation, "
                "not an outage)",
                boot.get("degraded_sources", 0),
            )
            sstats = (
                state.statestore.stats()
                if state.statestore is not None else {}
            )
            yield (
                metrics_names.STATESTORE_ARTIFACTS, "gauge",
                "Content-addressed policy artifacts resident in the "
                "state store's cache",
                sstats.get("artifacts_resident", 0),
            )
            yield (
                metrics_names.STATESTORE_BYTES, "gauge",
                "Bytes resident in the state store's artifact cache",
                sstats.get("bytes_resident", 0),
            )
            yield (
                metrics_names.STATESTORE_CACHE_HITS, "counter",
                "Artifact-cache hits (pinned warm-boot loads + degraded "
                "last-good fallbacks)",
                sstats.get("artifact_cache_hits", 0),
            )
            yield (
                metrics_names.STATESTORE_CACHE_MISSES, "counter",
                "Artifact-cache misses (url unknown, blob missing, or "
                "content-address verification failed)",
                sstats.get("artifact_cache_misses", 0),
            )
            yield (
                metrics_names.STATESTORE_MANIFESTS_PERSISTED, "counter",
                "Last-good epoch manifests persisted (boot, promotion, "
                "rollback — the durable rollback pin)",
                sstats.get("manifests_persisted", 0),
            )
            yield (
                metrics_names.STATESTORE_JOURNAL_RECORDS, "gauge",
                "Live records across the state store's journals "
                "(manifest history + url map)",
                sstats.get("journal_records", 0),
            )
            yield (
                metrics_names.STATESTORE_FSCK_QUARANTINED, "counter",
                "Torn/corrupt state-dir entries the fsck pass moved to "
                "quarantine (boot continued on surviving state)",
                sstats.get("fsck_quarantined", 0),
            )
            yield (
                metrics_names.STATESTORE_AUDIT_SPILLS, "counter",
                "Audit snapshot spills written (cursors + fed map + "
                "inventory, one atomic journal replace each)",
                sstats.get("audit_spills", 0),
            )
            yield (
                metrics_names.STATESTORE_AUDIT_ROWS_RESTORED, "gauge",
                "Audit inventory rows restored from the spill at this "
                "boot (the re-LIST the warm boot did NOT pay)",
                sstats.get("audit_rows_restored", 0),
            )
            sup = (
                state.supervisor.stats()
                if state.supervisor is not None else {}
            )
            yield (
                metrics_names.WORKER_RESPAWNS, "counter",
                "Prefork frontend workers respawned after dying",
                sup.get("worker_respawns", 0),
            )
            yield (
                metrics_names.WORKER_RESPAWN_BACKOFF_SECONDS, "counter",
                "Cumulative crash-loop backoff applied before worker "
                "respawns",
                sup.get("worker_backoff_seconds", 0.0),
            )
            yield (
                metrics_names.WORKER_SLOTS_GIVEN_UP, "gauge",
                "Frontend worker slots abandoned by the respawn breaker "
                "(crash-looped past the give-up cap; /readiness reports "
                "the degradation)",
                sup.get("worker_slots_given_up", 0),
            )
            yield (
                metrics_names.SELFHEAL_BATCHER_REVIVES, "counter",
                "Batcher dispatch loops the self-heal watchdog found "
                "dead and rebuilt",
                sup.get("batcher_revives", 0),
            )
            yield (
                metrics_names.SELFHEAL_FRONTEND_REVIVES, "counter",
                "Native-frontend drainer threads the self-heal watchdog "
                "found dead and rebuilt",
                sup.get("frontend_revives", 0),
            )
            # Serving shards (round 22, runtime/shards.py): the router's
            # health/fencing surface. With --serving-shards 1 the plain
            # batcher serves (no router object exists), so the gauges
            # report the one implicit shard and every fencing counter is
            # zero — the families still export so panels resolve.
            shard_rows = (
                batcher.shard_health()
                if hasattr(batcher, "shard_health") else []
            )
            yield (
                metrics_names.SHARDS_SERVING, "gauge",
                "Host-local serving shards behind the router "
                "(--serving-shards; 1 = router bypassed)",
                len(shard_rows) if shard_rows else 1,
            )
            yield (
                metrics_names.SHARD_HEALTHY, "gauge",
                "Per-shard routability (1 = routable, 0 = fenced "
                "pending warm revive)",
                [
                    ((str(r["shard"]),), 1 if r["healthy"] else 0)
                    for r in shard_rows
                ],
                ("shard",),
            )
            yield (
                metrics_names.SHARD_QUEUE_DEPTH, "gauge",
                "Per-shard submission queue depth (the router's "
                "EWMA routing signal reads this)",
                [
                    ((str(r["shard"]),), r["queue_depth"])
                    for r in shard_rows
                ],
                ("shard",),
            )
            yield (
                metrics_names.SHARD_FENCES, "counter",
                "Shards fenced by the heartbeat (wedged/dead dispatch "
                "loop or faulted probe)",
                bstats.get("shard_fences", 0),
            )
            yield (
                metrics_names.SHARD_REROUTED_ROWS, "counter",
                "Queued rows re-routed to a sibling shard at fence time "
                "(deadline, trace, and quota token preserved)",
                bstats.get("shard_reroutes", 0),
            )
            yield (
                metrics_names.SHARD_FENCED_ROWS, "counter",
                "Queued rows answered 503+Retry-After at fence time "
                "(no sibling had room)",
                bstats.get("shard_fenced_rows", 0),
            )
            yield (
                metrics_names.SHARD_RESPAWNS, "counter",
                "Fenced shards warm-revived in place (queue, pools, "
                "caches, and compiled programs survive)",
                bstats.get("shard_respawns", 0),
            )
            yield (
                metrics_names.SHARD_HEARTBEAT_FAULTS, "counter",
                "shard.heartbeat failpoint faults observed by the "
                "router's prober",
                bstats.get("shard_heartbeat_faults", 0),
            )
            # Persistent verdict matrix (round 23, audit/matrix.py):
            # residency, the row-vs-column sweep split, /audit/stream
            # fan-out accounting, the admission lookup fast path, and
            # the statestore spill/restore tie-in. All zero with
            # --audit-matrix off (families still export so dashboard
            # panels resolve everywhere).
            mstats = (
                state.audit_matrix.stats()
                if state.audit_matrix is not None
                else {}
            )
            yield (
                metrics_names.MATRIX_ROWS_RESIDENT, "gauge",
                "Distinct snapshot rows holding at least one verdict "
                "cell in the matrix",
                mstats.get("rows_resident", 0),
            )
            yield (
                metrics_names.MATRIX_CELLS_RESIDENT, "gauge",
                "Resident (object x policy) verdict cells",
                mstats.get("cells_resident", 0),
            )
            yield (
                metrics_names.MATRIX_COLUMNS, "gauge",
                "Policy columns of the serving epoch (keyed by policy "
                "content fingerprint, not epoch number)",
                mstats.get("columns", 0),
            )
            yield (
                metrics_names.MATRIX_DIRTY_COLUMNS, "gauge",
                "Columns awaiting a column-dirty sweep (epoch "
                "promotion changed their policy content)",
                mstats.get("dirty_columns", 0),
            )
            yield (
                metrics_names.MATRIX_VERSION, "gauge",
                "Monotonic matrix version — the /audit/stream resume "
                "cursor's upper bound",
                mstats.get("matrix_version", 0),
            )
            yield (
                metrics_names.MATRIX_ROW_SWEEP_ROWS, "counter",
                "Matrix rows re-judged because the watch feed dirtied "
                "the object row",
                mstats.get("row_sweep_rows", 0),
            )
            yield (
                metrics_names.MATRIX_COLUMN_SWEEP_ROWS, "counter",
                "Matrix rows re-judged because an epoch promotion "
                "dirtied the policy column",
                mstats.get("column_sweep_rows", 0),
            )
            yield (
                metrics_names.MATRIX_ROWS_EVICTED, "counter",
                "Matrix rows evicted by watch-feed DELETEs",
                mstats.get("rows_evicted", 0),
            )
            yield (
                metrics_names.MATRIX_COLUMNS_INVALIDATED, "counter",
                "Policy columns invalidated (content fingerprint "
                "changed or policy removed at promotion/rollback)",
                mstats.get("columns_invalidated", 0),
            )
            yield (
                metrics_names.MATRIX_CHANGELOG_EMITS, "counter",
                "Verdict-change entries emitted to the matrix "
                "changelog ring (re-stamps that confirm a standing "
                "verdict do not emit)",
                mstats.get("changelog_emits", 0),
            )
            yield (
                metrics_names.MATRIX_STREAM_CLIENTS, "gauge",
                "Connected GET /audit/stream subscribers",
                mstats.get("stream_clients", 0),
            )
            yield (
                metrics_names.MATRIX_STREAM_DROPPED_CLIENTS, "counter",
                "Stream subscribers dropped for slow consumption "
                "(bounded per-client queue overflowed; the applier "
                "never blocks)",
                mstats.get("changelog_dropped_clients", 0),
            )
            yield (
                metrics_names.MATRIX_LOOKUP_HITS, "counter",
                "/validate requests answered from a precomputed "
                "matrix verdict (byte-identical UPDATE payload, "
                "protect-mode hookless target)",
                bstats.get("matrix_lookup_hits", 0),
            )
            yield (
                metrics_names.MATRIX_LOOKUP_MISSES, "counter",
                "Matrix-eligible /validate requests that fell through "
                "to full evaluation (no cell, stale payload hash, or "
                "stale column fingerprint)",
                bstats.get("matrix_lookup_misses", 0),
            )
            yield (
                metrics_names.MATRIX_SPILLS, "counter",
                "Matrix spills journaled to the statestore "
                "(cadenced sweep-tail spills + the shutdown spill)",
                mstats.get("spills", 0),
            )
            yield (
                metrics_names.MATRIX_CELLS_RESTORED, "gauge",
                "Verdict cells restored from the statestore spill at "
                "warm boot (column fingerprint + payload hash matched)",
                mstats.get("cells_restored", 0),
            )
            # Flight recorder (round 18, telemetry/flightrec.py): event
            # volume, row-sampling volume, and the tail-exemplar table —
            # the slowest rows of the current window, labelled by their
            # trace id (request uid) so a dashboard p99 blip links to
            # its /debug/timeline. The sample set rebuilds per scrape,
            # so rotated-out exemplars disappear instead of lingering.
            # All zero/empty with --flight-recorder off (families still
            # export so dashboard panels resolve everywhere).
            from policy_server_tpu.telemetry import flightrec as _frec

            frec = _frec.recorder()
            yield (
                metrics_names.FLIGHT_RECORDER_EVENTS, "counter",
                "Phase events written to the flight-recorder ring",
                frec.events_recorded() if frec is not None else 0,
            )
            yield (
                metrics_names.FLIGHT_RECORDER_ROWS_SAMPLED, "counter",
                "Rows that recorded per-row timeline segments "
                "(--recorder-row-sample-rate stride)",
                frec.rows_sampled() if frec is not None else 0,
            )
            yield (
                metrics_names.TAIL_EXEMPLAR_LATENCY_SECONDS, "gauge",
                "Tail exemplars: the slowest rows of the current "
                "flight-recorder window, with trace id and slowest "
                "phase (full phase breakdown on /debug/timeline)",
                [
                    (
                        (
                            ex["trace_id"], ex["policy_id"],
                            ex["slowest_phase"],
                        ),
                        ex["latency_seconds"],
                    )
                    for ex in (
                        frec.exemplars() if frec is not None else ()
                    )
                ],
                ("trace_id", "policy_id", "slowest_phase"),
            )

        from policy_server_tpu.telemetry import default_registry

        default_registry().attach_runtime_stats(runtime_stats)

        tls_context = None
        if config.tls_config.enabled:
            try:
                from policy_server_tpu.certs import (
                    create_tls_config_and_watch_certificate_changes,
                )
            except ImportError as e:
                raise RuntimeError(
                    "TLS was configured but the certs subsystem is not "
                    "available"
                ) from e
            tls_context = create_tls_config_and_watch_certificate_changes(
                config.tls_config
            )
            # cert-expiry/reload observability reads the last-good
            # identity machinery through the state, independent of
            # which frontend terminates the handshake
            state.tls_reloadable = getattr(
                tls_context, "_reloadable", None
            )

        # -- boot report (round 17): how warm this boot actually was ------
        # "warm" = the state store carried a last-good manifest forward;
        # the drill additionally checks artifacts_from_cache/fetches to
        # prove the zero-network property.
        if statestore is not None:
            ss = statestore.stats()
            boot_report.update(
                warm=bool(boot_report.get("manifest_found")),
                time_to_ready_seconds=round(
                    _time.monotonic() - boot_t0, 3
                ),
                artifacts_from_cache=ss["artifact_cache_hits"],
                degraded_sources=boot_report.get("degraded_sources", 0)
                + ss["degraded_loads"],
                fsck_quarantined=ss["fsck_quarantined"],
            )
            try:
                from policy_server_tpu.fetch.downloader import retry_stats

                boot_report["fetch_retry_giveups"] = retry_stats()["giveups"]
            except ImportError:
                pass
            statestore.record_boot_report(boot_report)
            logger.info(
                "boot report", extra={"span_fields": dict(boot_report)}
            )
        else:
            boot_report["time_to_ready_seconds"] = round(
                _time.monotonic() - boot_t0, 3
            )

        return cls(config, state, tls_context)

    # -- routers (lib.rs:282 router(); used directly by in-process tests) --

    def router(self) -> web.Application:
        return build_router(self.state)

    def readiness_router(self) -> web.Application:
        return build_readiness_router(self.state)

    # -- run loop (lib.rs:238-280) -----------------------------------------

    async def start(self) -> None:
        """Bind both servers; returns once serving (used by run() and by
        socket-based tests, which read the bound ports)."""
        prefork = self.config.http_workers > 1 and self.tls_context is None
        if self.config.http_workers > 1 and self.tls_context is not None:
            logger.warning(
                "--http-workers is not supported with TLS yet (workers "
                "would each need the cert material); serving in-process"
            )
        native = False
        if self.config.frontend == "native":
            if (
                self.tls_context is not None
                and self.config.native_tls == "off"
            ):
                logger.warning(
                    "--native-tls off with --frontend native: TLS "
                    "terminates on the aiohttp frontend (the native "
                    "loops cannot share its port); serving with the "
                    "Python frontend"
                )
            else:
                native = self._start_native_frontend()
        if not native:
            api_runner = web.AppRunner(self.router())
            await api_runner.setup()
            api_site = web.TCPSite(
                api_runner, self.config.addr, self.config.port,
                ssl_context=self.tls_context,
                reuse_port=prefork or None,
            )
            await api_site.start()
            self.api_port = _bound_port(api_runner) or self.config.port
            self._runners.append(api_runner)
        if prefork:
            await self._start_frontend_workers()

        # readiness server starts only after the API server is bound
        # (Notify handshake, lib.rs:239-268)
        ready_runner = web.AppRunner(self.readiness_router())
        await ready_runner.setup()
        ready_site = web.TCPSite(
            ready_runner, self.config.addr, self.config.readiness_probe_port
        )
        await ready_site.start()
        self.readiness_port = _bound_port(ready_runner) or (
            self.config.readiness_probe_port
        )
        self._runners.append(ready_runner)

        if (
            self.config.selfheal_interval_seconds > 0
            and self.state.supervisor is not None
        ):
            from policy_server_tpu.supervision import SelfHealWatchdog

            self._selfheal = SelfHealWatchdog(
                self.state,
                self.state.supervisor,
                interval_seconds=self.config.selfheal_interval_seconds,
            ).start()

        self._ready.set()
        logger.info(
            "policy server started",
            extra={
                "span_fields": {
                    "addr": self.config.addr,
                    "port": self.api_port,
                    "readiness_probe_port": self.readiness_port,
                    "tls": self.tls_context is not None,
                    "policies": len(self.environment.policy_ids()),
                }
            },
        )

    def _start_native_frontend(self) -> bool:
        """Bind the GIL-free C++ HTTP front-end on the API port (it then
        OWNS the evaluation POST surface; pprof and /audit/reports GETs
        live on the readiness port). Returns False — with ONE loud line —
        on any build/load/bind failure, and the caller serves through the
        always-available Python frontend instead (the round-7 soft-dep
        pattern: degraded, never broken). With TLS configured, the
        handshake terminates ON the native epoll loops (round 20):
        certs.py's last-good identity builds the SSL_CTX, hot-rotation
        swaps it for NEW connections while established ones drain on
        the old, and a missing/unlinkable libssl falls back LOUDLY to
        the aiohttp TLS terminator — degraded in throughput, identical
        in trust surface."""
        sock = None
        tls_manager = None
        try:
            from policy_server_tpu.api.handlers import MAX_BODY_BYTES
            from policy_server_tpu.runtime import native_frontend as nf

            if not nf.native_available():
                raise RuntimeError(
                    "csrc/httpfront.cpp failed to build or load"
                )
            # one body cap across every process that can accept the API
            # socket — a drift here would make 413s nondeterministic
            # behind SO_REUSEPORT
            assert nf.MAX_BODY_BYTES == MAX_BODY_BYTES
            sock = nf.make_listen_socket(self.config.addr, self.config.port)
            front = nf.NativeFrontend(
                sock, nf.BatcherSink(self.state), max_body=MAX_BODY_BYTES,
                idle_timeout_ms=int(
                    self.config.native_idle_timeout_seconds * 1000
                ),
                read_timeout_ms=int(
                    self.config.native_read_timeout_seconds * 1000
                ),
                max_connections=self.config.native_max_connections,
            )
            if self.tls_context is not None:
                if not nf.tls_available():
                    raise RuntimeError(
                        f"native TLS unavailable ({nf.tls_error()}); "
                        "TLS will terminate on the aiohttp frontend"
                    )
                reloadable = getattr(self.tls_context, "_reloadable", None)
                if reloadable is None:
                    raise RuntimeError(
                        "TLS context carries no reloadable identity "
                        "(embedding without certs.py?)"
                    )
                tls_manager = nf.NativeTlsManager(
                    front, reloadable,
                    handshake_timeout_ms=int(
                        self.config.native_tls_handshake_timeout_seconds
                        * 1000
                    ),
                )
            front.start()
        except Exception as e:  # noqa: BLE001 — fall back, never refuse boot
            if tls_manager is not None:
                import contextlib

                with contextlib.suppress(Exception):
                    tls_manager.stop()
            if sock is not None:
                import contextlib

                with contextlib.suppress(OSError):
                    sock.close()
            logger.warning(
                "native HTTP frontend unavailable (%s); falling back to "
                "the Python frontend", e,
            )
            return False
        self._native_frontend = front
        self.state.native_frontend = front
        self._native_tls = tls_manager
        self.state.native_tls = tls_manager
        self.api_port = sock.getsockname()[1]
        if self.config.enable_pprof:
            logger.warning(
                "--enable-pprof with --frontend native: the native "
                "frontend serves only the evaluation POST surface; hit "
                "the pprof endpoints with --frontend python"
            )
        logger.info(
            "native HTTP frontend started",
            extra={"span_fields": {
                "addr": self.config.addr, "port": self.api_port,
                "tls": tls_manager is not None,
                "ktls": (
                    tls_manager.snapshot()["ktls"]
                    if tls_manager is not None else False
                ),
            }},
        )
        return True

    async def _start_frontend_workers(self) -> None:
        """Spawn the prefork HTTP workers (runtime/frontend.py): the
        evaluation bridge on a unix socket, then N lightweight processes
        binding the already-bound API port with SO_REUSEPORT."""
        import os as _os
        import subprocess
        import sys
        import tempfile

        from policy_server_tpu.runtime.frontend import EvaluationBridge

        # 0700 private directory: a world-writable /tmp path would let any
        # local user squat the socket name or connect to the evaluation
        # bridge directly, bypassing the HTTP listener's TLS/auth surface
        bridge_dir = tempfile.mkdtemp(prefix="policy-server-bridge-")
        _os.chmod(bridge_dir, 0o700)
        self._bridge_dir = bridge_dir
        self._bridge_socket = _os.path.join(bridge_dir, "bridge.sock")
        self._bridge = EvaluationBridge(self.state, self._bridge_socket)
        await self._bridge.start()
        n = self.config.http_workers - 1  # this process serves too
        self._worker_cmd = [
            sys.executable,
            "-m",
            "policy_server_tpu.runtime.frontend",
            "--socket", self._bridge_socket,
            "--addr", self.config.addr,
            "--port", str(self.api_port),
            "--hostname", self.config.hostname,
            "--log-level", self.config.log_level,
            "--log-fmt",
            self.config.log_fmt
            if self.config.log_fmt != "otlp"
            else "json",  # workers log; spans stay in-process
            "--frontend", self.config.frontend,
        ]
        for i in range(n):
            self._worker_procs.append(subprocess.Popen(self._worker_cmd))
        logger.info(
            "prefork HTTP frontend started",
            extra={"span_fields": {
                "workers": n + 1, "bridge": self._bridge_socket,
            }},
        )
        self._worker_supervisor = asyncio.ensure_future(
            self._supervise_workers()
        )

    _WORKER_RESPAWN_INTERVAL_SECONDS = 2.0
    # crash-loop discipline (the reference defers to kubelet's restart
    # backoff; the in-box supervisor needs the same): a worker dying
    # within the crash window of its spawn is a crash-loop death —
    # respawn with exponential backoff, give up on the slot after the
    # --worker-respawn-giveup cap of consecutive fast deaths (a worker
    # that boots on a bad port/config would otherwise respawn forever
    # at 0.5 Hz). The give-up is the RESPAWN BREAKER: readiness then
    # reports the degraded slot honestly, and the counters export.
    _WORKER_CRASH_WINDOW_SECONDS = 5.0
    _WORKER_BACKOFF_BASE_SECONDS = 0.5
    _WORKER_BACKOFF_CAP_SECONDS = 30.0

    async def _supervise_workers(self) -> None:
        """Respawn dead frontend workers (the in-box analog of kubelet
        restarting reference replicas): a crashed worker otherwise shrinks
        the SO_REUSEPORT accept pool until restart. Fast-crashing workers
        back off exponentially and the slot is abandoned after
        ``--worker-respawn-giveup`` consecutive fast deaths."""
        import subprocess
        import time as _time

        giveup = self.config.worker_respawn_giveup
        supervisor = self.state.supervisor
        now = _time.monotonic()
        spawned_at = [now] * len(self._worker_procs)
        fast_deaths = [0] * len(self._worker_procs)
        respawn_at = [0.0] * len(self._worker_procs)

        while True:
            await asyncio.sleep(self._WORKER_RESPAWN_INTERVAL_SECONDS)
            now = _time.monotonic()
            for i, proc in enumerate(list(self._worker_procs)):
                if (
                    proc is None
                    or isinstance(proc, _PendingRespawn)
                    or proc.poll() is None
                ):
                    continue
                lifetime = now - spawned_at[i]
                if lifetime < self._WORKER_CRASH_WINDOW_SECONDS:
                    fast_deaths[i] += 1
                else:
                    fast_deaths[i] = 0
                if fast_deaths[i] >= giveup:
                    logger.error(
                        "frontend worker slot %d crash-looped %d times "
                        "within %.1fs of spawn (rc=%s); giving up on the "
                        "slot — the remaining processes keep serving",
                        i, fast_deaths[i],
                        self._WORKER_CRASH_WINDOW_SECONDS, proc.returncode,
                    )
                    self._worker_procs[i] = None
                    # SupervisorStats is the ONE authority for the
                    # give-up count (readiness + /metrics read it)
                    if supervisor is not None:
                        supervisor.count_slot_given_up()
                    continue
                backoff = 0.0
                if fast_deaths[i]:
                    backoff = min(
                        self._WORKER_BACKOFF_CAP_SECONDS,
                        self._WORKER_BACKOFF_BASE_SECONDS
                        * 2 ** (fast_deaths[i] - 1),
                    )
                respawn_at[i] = now + backoff
                logger.warning(
                    "frontend worker died (rc=%s, lived %.1fs); respawning "
                    "in %.1fs (consecutive fast deaths: %d)",
                    proc.returncode, lifetime, backoff, fast_deaths[i],
                )
                # mark the slot pending; actual spawn below when due
                self._worker_procs[i] = _PendingRespawn(proc.returncode)
                if supervisor is not None:
                    supervisor.count_respawn(backoff)
            for i, proc in enumerate(list(self._worker_procs)):
                if (
                    isinstance(proc, _PendingRespawn)
                    and now >= respawn_at[i]
                ):
                    self._worker_procs[i] = subprocess.Popen(self._worker_cmd)
                    spawned_at[i] = _time.monotonic()

    async def stop(self) -> None:
        import contextlib
        import os as _os

        if self._selfheal is not None:
            # the watchdog goes FIRST: shutting-down threads must not be
            # mistaken for wedged ones and "revived" mid-teardown
            self._selfheal.stop()
            self._selfheal = None
        if self._native_frontend is not None:
            # stop ACCEPTING first; in-flight native requests drain below
            # once the batcher shutdown resolves their futures
            self._native_frontend.stop_accepting()
        supervisor = getattr(self, "_worker_supervisor", None)
        if supervisor is not None:
            supervisor.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await supervisor
            self._worker_supervisor = None

        live_procs = [
            p for p in self._worker_procs
            if p is not None and not isinstance(p, _PendingRespawn)
        ]
        for proc in live_procs:
            with contextlib.suppress(ProcessLookupError):
                proc.terminate()
        loop = asyncio.get_running_loop()
        for proc in live_procs:
            try:
                # off-loop wait: a wedged worker must not stall shutdown's
                # event loop; escalate to SIGKILL so no orphan keeps a
                # share of the SO_REUSEPORT port serving 503s
                await loop.run_in_executor(None, proc.wait, 5)
            except Exception:  # noqa: BLE001 — TimeoutExpired and friends
                with contextlib.suppress(ProcessLookupError):
                    proc.kill()
                with contextlib.suppress(Exception):
                    await loop.run_in_executor(None, proc.wait, 5)
        self._worker_procs.clear()
        if self._bridge is not None:
            await self._bridge.stop()
            self._bridge = None
        if self._bridge_socket:
            with contextlib.suppress(OSError):
                _os.unlink(self._bridge_socket)
            self._bridge_socket = None
        if getattr(self, "_bridge_dir", None):
            with contextlib.suppress(OSError):
                _os.rmdir(self._bridge_dir)
            self._bridge_dir = None
        for runner in self._runners:
            await runner.cleanup()
        self._runners.clear()
        if self.state.audit_watch is not None:
            # stop the live feed BEFORE the scanner: a watcher applying
            # events into a store nobody will sweep again is dead work
            self.state.audit_watch.stop()
            self.state.audit_watch = None
        if self.state.audit is not None:
            # stop sweeping BEFORE epochs tear down: a sweep racing the
            # batcher shutdown would only burn its retry budget
            self.state.audit.shutdown()
        if self.state.tenants is not None:
            # named tenants tear down first (each lifecycle closes its
            # own epochs); the default tenant follows the paths below
            self.state.tenants.shutdown()
        if self.lifecycle is not None:
            # the lifecycle manager owns every epoch (current, pinned
            # previous, staged): one teardown path closes them all
            self.lifecycle.shutdown()
        else:
            self.batcher.shutdown()
            # The server built the environment, so the server closes it —
            # the batcher only borrows it (two batchers may share one env).
            self.environment.close()
        if self._native_tls is not None:
            # the TLS manager stops BEFORE the loops tear down: its
            # failpoint poll thread and reload listener must not touch
            # a frontend handle mid-destroy
            self._native_tls.stop()
            self._native_tls = None
            self.state.native_tls = None
        if self._native_frontend is not None:
            # every submitted future is resolved by now (batcher shutdown
            # drains rejecting), so this just flushes the last completions
            # out of the sockets, then stops the native loops
            await asyncio.get_running_loop().run_in_executor(
                None, self._native_frontend.shutdown
            )
            self._native_frontend = None
            self.state.native_frontend = None
        # Flush buffered spans / final metric state to the collector (the
        # reference flushes its OTEL providers on shutdown). No-op when the
        # OTLP pipeline was never installed.
        from policy_server_tpu.telemetry import otlp

        otlp.shutdown_pipeline()

    def reload_signal(self) -> None:
        """The SIGHUP contract: ONE signal drives both hot-reload paths —
        the TLS identity/client-CA reload (certs.py reload_now, forced
        regardless of the change detector) and the policy-set reload
        (lifecycle.py, background fetch+compile+canary). Both keep
        last-good state on any failure, so a SIGHUP can never make the
        server worse. Safe to invoke from a signal handler context: all
        real work happens on daemon threads."""
        reloadable = getattr(self.tls_context, "_reloadable", None)
        if reloadable is not None:
            import threading

            threading.Thread(
                target=reloadable.reload_now,
                name="sighup-cert-reload",
                daemon=True,
            ).start()
        if self.state.tenants is not None:
            # multi-tenant: one SIGHUP kicks EVERY tenant's independent
            # reload pipeline (the default included); each failure is
            # contained to its tenant
            self.state.tenants.reload_all("sighup")
        elif self.lifecycle is not None:
            self.lifecycle.request_reload("sighup")

    async def run_async(self) -> None:
        """Serve until cancelled or signalled. SIGTERM/SIGINT trigger the
        same graceful stop (drain batcher futures, close the environment,
        flush OTLP) — a pod rolling update must not drop buffered spans or
        strand in-flight webhook calls. SIGHUP triggers the combined
        cert + policy hot reload (reload_signal)."""
        import signal

        await self.start()
        stop_requested = asyncio.Event()
        loop = asyncio.get_running_loop()
        registered: list[int] = []
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(sig, stop_requested.set)
                registered.append(sig)
            except (NotImplementedError, RuntimeError):
                pass  # non-main thread / platform without signal support
        # SIGHUP → hot reload, same off-main-thread guard as above (a
        # server embedded in a thread simply has no signal trigger; the
        # admin endpoint and file watcher still drive reloads)
        sighup = getattr(signal, "SIGHUP", None)
        if sighup is not None:
            try:
                loop.add_signal_handler(sighup, self.reload_signal)
                registered.append(sighup)
            except (NotImplementedError, RuntimeError):
                pass
        try:
            await stop_requested.wait()
            logger.info("shutdown signal received, stopping gracefully")
        except asyncio.CancelledError:
            pass
        finally:
            for sig in registered:
                loop.remove_signal_handler(sig)
            await self.stop()

    def run(self) -> None:
        """Blocking entry (reference PolicyServer::run, lib.rs:238)."""
        asyncio.run(self.run_async())


def run_server(args) -> int:
    """Process entry used by the CLI (reference main.rs:15-65): config →
    tracing/metrics setup → optional daemonize → bootstrap → run."""
    from policy_server_tpu.telemetry import setup_tracing

    config = Config.from_args(args)
    setup_tracing(config.log_level, config.log_fmt, config.log_no_color)
    if config.daemon:
        _daemonize(config)
    server = PolicyServer.new_from_config(config)
    try:
        server.run()
    except KeyboardInterrupt:
        pass
    return 0


def _daemonize(config: Config) -> None:
    """Double-fork daemonization (reference main.rs:35-55, daemonize crate):
    detach, write the pid file, redirect stdout/stderr."""
    import os
    import sys

    if os.fork() > 0:
        os._exit(0)
    os.setsid()
    if os.fork() > 0:
        os._exit(0)
    with open(config.daemon_pid_file, "w", encoding="utf-8") as f:
        f.write(str(os.getpid()))
    sys.stdout.flush()
    sys.stderr.flush()
    out = open(config.daemon_stdout_file or os.devnull, "ab")
    err = open(config.daemon_stderr_file or os.devnull, "ab")
    os.dup2(out.fileno(), sys.stdout.fileno())
    os.dup2(err.fileno(), sys.stderr.fileno())


def _jax_version() -> str:
    """The jax version string for the compile fingerprint (a version
    bump invalidates the persistent XLA cache's hit expectations); ""
    when the backend is not importable (oracle-only deployments)."""
    try:
        import jax

        return str(jax.__version__)
    except ImportError:
        return ""


def _bound_port(runner: web.AppRunner) -> int | None:
    for site in runner.sites:
        server = getattr(site, "_server", None)
        if server and server.sockets:
            return server.sockets[0].getsockname()[1]
    return None


def _build_audit_watch_feed(
    config: Config, snapshot_store, statestore=None, resume=None
):
    """--audit-watch bring-up: the in-cluster list+watch client feeding
    the audit snapshot store (audit/watch_feed.py). Connection failure
    follows the context-service contract: fatal unless
    --ignore-kubernetes-connection-failure, which degrades to the
    dirty-tracking + seed-file feeds with a loud error."""
    from policy_server_tpu.audit import WatchFeed, parse_watch_resources
    from policy_server_tpu.context import KubeApiFetcher, KubeConnectionError

    resources = parse_watch_resources(config.audit_watch_resources)
    try:
        fetcher = KubeApiFetcher(
            insecure_skip_tls_verify=config.kube_insecure_skip_tls_verify
        )
    except KubeConnectionError as e:
        if not config.ignore_kubernetes_connection_failure:
            raise RuntimeError(
                f"--audit-watch cannot connect to the Kubernetes API: {e} "
                "(use --ignore-kubernetes-connection-failure to boot "
                "without the live feed)"
            ) from e
        logger.error(
            "Kubernetes connection failed; the audit snapshot store "
            "falls back to /validate dirty-tracking and the seed file: "
            "%s", e,
        )
        return None
    return WatchFeed(
        fetcher,
        resources,
        snapshot_store,
        refresh_seconds=config.context_refresh_seconds,
        max_queue_events=config.audit_watch_max_queue_events,
        statestore=statestore,
        spill_interval_seconds=config.state_audit_spill_seconds,
        resume_rvs=(resume or {}).get("rvs"),
        resume_fed=(resume or {}).get("fed"),
    ).start()


def _build_context_service(config: Config):
    """Context-snapshot bring-up (reference kube::Client bootstrap,
    lib.rs:91-125): only when some policy declares contextAwareResources;
    connection failure is fatal unless --ignore-kubernetes-connection-failure
    (lib.rs:106-123), in which case context-aware policies see an empty
    cluster."""
    wanted: set = set()
    for entry in config.policies.values():
        if hasattr(entry, "context_aware_resources"):
            wanted |= set(entry.context_aware_resources)
        elif hasattr(entry, "policies"):
            for member in entry.policies.values():
                wanted |= set(member.context_aware_resources)
    if not wanted:
        return None
    from policy_server_tpu.context import (
        ContextSnapshotService,
        KubeApiFetcher,
        KubeConnectionError,
        StaticContextFetcher,
    )

    try:
        fetcher = KubeApiFetcher(
            insecure_skip_tls_verify=config.kube_insecure_skip_tls_verify
        )
    except KubeConnectionError as e:
        if not config.ignore_kubernetes_connection_failure:
            raise RuntimeError(
                f"cannot connect to the Kubernetes API: {e} "
                "(use --ignore-kubernetes-connection-failure to boot anyway)"
            ) from e
        logger.error(
            "Kubernetes connection failed, context-aware policies will see "
            "an empty cluster: %s", e,
        )
        fetcher = StaticContextFetcher()
    return ContextSnapshotService(
        fetcher,
        wanted,
        refresh_seconds=config.context_refresh_seconds,
        # None = auto (watch when the fetcher supports it); False = forced
        # poll mode via --context-no-watch
        watch=None if config.context_watch else False,
    ).start()


def _build_environment(config: Config, builder_kwargs: dict):
    """Build the evaluation environment, honoring ``config.mesh`` and
    ``config.mesh_dispatch``.

    TPU-first serving topology (SURVEY.md §2.3 last row; the reference's
    scale-out is replicas behind a Service, README.md:21-26):

    * >1 device on the mesh → ONE fused SPMD program over the whole
      (data × policy) mesh via ``attach_mesh`` (round 14): batch planes
      shard on ``data``, a >1 ``policy`` axis additionally buckets the
      policy set into per-shard ``lax.switch`` branches whose verdict
      blocks meet in an all-gather collective — one device program per
      batch.
    * ``policy`` axis > 1 with ``--mesh-dispatch threaded`` →
      :class:`PolicyShardedEvaluator`, the legacy MPMD fallback: one
      fused program per policy shard on its own submesh row, dispatched
      from a host thread pool.
    * single device (the default ``auto`` spec on a 1-chip host) → plain
      single-device environment, unchanged.
    """
    mesh = None
    if config.evaluation_backend == "jax":
        from policy_server_tpu.parallel import make_mesh

        mesh = make_mesh(config.mesh)
        if (
            config.mesh.policy_size() > 1
            and config.mesh_dispatch == "threaded"
        ):
            from policy_server_tpu.parallel import PolicyShardedEvaluator

            sharded = PolicyShardedEvaluator(
                config.policies,
                mesh,
                backend=config.evaluation_backend,
                continue_on_errors=config.continue_on_errors,
                builder_kwargs=builder_kwargs,
            )
            logger.info(
                "policy-sharded mesh attached (threaded MPMD fallback)",
                extra={"span_fields": {
                    "mesh": dict(config.mesh.axes),
                    "shards": len(sharded.shards),
                }},
            )
            return sharded

    builder = EvaluationEnvironmentBuilder(
        backend=config.evaluation_backend,
        continue_on_errors=config.continue_on_errors,
        **builder_kwargs,
    )
    environment = builder.build(config.policies)
    if mesh is not None and mesh.devices.size > 1:
        environment.attach_mesh(mesh)
        logger.info(
            "fused SPMD mesh attached",
            extra={"span_fields": {"mesh": dict(config.mesh.axes),
                                   "devices": int(mesh.devices.size),
                                   "policy_sharded":
                                       environment._mesh_block is not None}},
        )
    return environment


def _needs_fetch(config: Config) -> bool:
    """True when any configured module URL is not a builtin."""
    from policy_server_tpu.policies import resolve_builtin

    urls: list[str] = []
    for entry in config.policies.values():
        if hasattr(entry, "module"):
            urls.append(entry.module)
        else:
            urls.extend(m.module for m in entry.policies.values())
    return any(resolve_builtin(u) is None for u in urls)
