"""CEL → predicate-IR lowering: CEL validations compile onto the TPU.

The device path is the point of this framework, so CEL expressions lower
to the same IR the builtin library uses (ops/ir.py) whenever they fit the
IR's shape: path comparisons, string predicates, membership, and the
all/exists/exists_one macros → AllOf/AnyOf/CountOf quantifiers. What
doesn't fit (arithmetic on fields, ternaries, map construction, cross-
scope macro variables) raises :class:`CelLoweringError` and the policy
falls back to the host CEL interpreter (cel/interp.py) — the same
fast-path/escape-hatch split as the rest of the build.

Semantics note (documented divergence): IR comparisons on MISSING fields
are False (codec semantics), while real CEL errors on missing fields —
both produce a deny for a bare failed validation, but guard idioms like
``has(object.spec.x) && object.spec.x > 3`` behave identically and are
the recommended form. ``params.<key>`` resolves from the policy settings
at build time (the Kubernetes ValidatingAdmissionPolicy naming).
"""

from __future__ import annotations

from typing import Any, Mapping

from policy_server_tpu.cel import parser as P
from policy_server_tpu.ops import ir
from policy_server_tpu.ops.ir import CmpOp, Const, DType, Expr


class CelLoweringError(ValueError):
    """Expression is outside the IR-lowerable subset."""


# The validate payload root is the AdmissionRequest document itself
# (models/admission.py payload(); library policies address e.g.
# Path("object.spec.containers") the same way)
_ROOTS: dict[str, tuple[str, ...]] = {
    "request": (),
    "object": ("object",),
    "oldObject": ("oldObject",),
}

_STR_METHODS = {
    "contains": "contains",
    "startsWith": "prefix",
    "endsWith": "suffix",
    "matches": "regex",
}

_CMP = {
    "==": CmpOp.EQ, "!=": CmpOp.NE, "<": CmpOp.LT,
    "<=": CmpOp.LE, ">": CmpOp.GT, ">=": CmpOp.GE,
}


def _dtype_of_value(v: Any) -> DType:
    if isinstance(v, bool):
        return DType.BOOL
    if isinstance(v, int):
        return DType.I32
    if isinstance(v, float):
        return DType.F32
    if isinstance(v, str):
        return DType.ID
    raise CelLoweringError(f"unsupported literal type {type(v).__name__}")


class _PathRef:
    """A resolved CEL selection chain: absolute or element-relative."""

    __slots__ = ("kind", "segments")

    def __init__(self, kind: str, segments: tuple[str, ...]):
        self.kind = kind  # 'abs' | 'elem'
        self.segments = segments

    def leaf(self, dtype: DType):
        if self.kind == "abs":
            return ir.Path(self.segments, dtype)
        return ir.Elem(self.segments, dtype)

    def extended(self, field: str) -> "_PathRef":
        return _PathRef(self.kind, self.segments + (field,))


class Lowerer:
    def __init__(self, params: Mapping[str, Any]):
        self.params = dict(params or {})
        # var name → _PathRef ('abs' survives macro nesting; 'elem' refers
        # to the INNERMOST quantifier only, so entering a nested macro
        # invalidates outer elem vars — IR has one element scope)
        self.env: dict[str, _PathRef] = {}

    # -- resolution ---------------------------------------------------------

    def _resolve_param(self, node: Any) -> Any:
        """params.<a>.<b>… → the settings value, or raise."""
        chain: list[str] = []
        cur = node
        while isinstance(cur, P.Select):
            chain.append(cur.field)
            cur = cur.base
        if not (isinstance(cur, P.Ident) and cur.name == "params"):
            raise CelLoweringError("not a params reference")
        value: Any = self.params
        for field in reversed(chain):
            if not isinstance(value, Mapping) or field not in value:
                raise CelLoweringError(
                    f"params.{'.'.join(reversed(chain))} not present in settings"
                )
            value = value[field]
        return value

    def _as_path(self, node: Any) -> _PathRef:
        """Selection chain → _PathRef; raises when not a pure path."""
        if isinstance(node, P.Ident):
            if node.name in self.env:
                return self.env[node.name]
            root = _ROOTS.get(node.name)
            if root is None:
                raise CelLoweringError(f"unknown identifier {node.name!r}")
            return _PathRef("abs", root)
        if isinstance(node, P.Select):
            return self._as_path(node.base).extended(node.field)
        raise CelLoweringError(f"not a field path: {type(node).__name__}")

    def _const_value(self, node: Any) -> Any:
        if isinstance(node, P.Lit):
            return node.value
        if isinstance(node, P.ListLit):
            return [self._const_value(x) for x in node.items]
        try:
            return self._resolve_param(node)
        except CelLoweringError:
            raise CelLoweringError(
                f"expected a constant, got {type(node).__name__}"
            ) from None

    # -- lowering -----------------------------------------------------------

    def lower(self, node: Any) -> Expr:
        """AST → boolean IR expression."""
        if isinstance(node, P.Lit):
            if isinstance(node.value, bool):
                return ir.true() if node.value else ir.false()
            raise CelLoweringError("non-boolean literal in boolean position")
        if isinstance(node, P.Unary) and node.op == "!":
            return ir.Not(self.lower(node.operand))
        if isinstance(node, P.Binary):
            return self._lower_binary(node)
        if isinstance(node, P.Call):
            return self._lower_call(node)
        if isinstance(node, (P.Ident, P.Select)):
            # a bare boolean field: object.spec.hostNetwork
            return ir.Cmp(
                CmpOp.EQ, self._as_path(node).leaf(DType.BOOL), Const(True, DType.BOOL)
            )
        raise CelLoweringError(
            f"unsupported construct {type(node).__name__} in boolean position"
        )

    def _lower_binary(self, node: P.Binary) -> Expr:
        op = node.op
        if op == "&&":
            return ir.And((self.lower(node.lhs), self.lower(node.rhs)))
        if op == "||":
            return ir.Or((self.lower(node.lhs), self.lower(node.rhs)))
        if op == "in":
            return self._lower_in(node)
        if op in _CMP:
            return self._lower_cmp(node)
        raise CelLoweringError(f"operator {op!r} does not lower to IR")

    def _lower_cmp(self, node: P.Binary) -> Expr:
        op = _CMP[node.op]
        # size(x) <op> N
        for size_side, const_side, flip in (
            (node.lhs, node.rhs, False),
            (node.rhs, node.lhs, True),
        ):
            if (
                isinstance(size_side, P.Call)
                and size_side.name == "size"
            ):
                count = self._lower_size(size_side)
                value = self._const_value(const_side)
                if isinstance(value, bool) or not isinstance(value, int):
                    raise CelLoweringError("size() compares to an integer")
                cmp_op = _FLIPPED[op] if flip else op
                return ir.Cmp(cmp_op, count, Const(value, DType.I32))
        # path <op> const | const <op> path. Path-vs-path comparisons do
        # NOT lower: the leaf dtypes are unknowable statically and a wrong
        # guess silently mis-encodes (ID-typed numeric leaves read as
        # MISSING) — the host interpreter handles them with real values.
        lhs_path = self._try_path(node.lhs)
        rhs_path = self._try_path(node.rhs)
        if lhs_path is not None and rhs_path is not None:
            raise CelLoweringError(
                "field-to-field comparisons need the host interpreter"
            )
        if lhs_path is not None:
            value = self._const_value(node.rhs)
            dtype = _dtype_of_value(value)
            return ir.Cmp(op, lhs_path.leaf(dtype), Const(value, dtype))
        if rhs_path is not None:
            value = self._const_value(node.lhs)
            dtype = _dtype_of_value(value)
            return ir.Cmp(_FLIPPED[op], rhs_path.leaf(dtype), Const(value, dtype))
        raise CelLoweringError("comparison needs at least one field path")

    def _try_path(self, node: Any) -> _PathRef | None:
        if not isinstance(node, (P.Ident, P.Select)):
            return None
        if self._is_params_ref(node):
            return None
        try:
            return self._as_path(node)
        except CelLoweringError:
            return None

    @staticmethod
    def _is_params_ref(node: Any) -> bool:
        cur = node
        while isinstance(cur, P.Select):
            cur = cur.base
        return isinstance(cur, P.Ident) and cur.name == "params"

    def _lower_in(self, node: P.Binary) -> Expr:
        lhs_path = self._try_path(node.lhs)
        if lhs_path is not None:
            values = self._const_value(node.rhs)
            if not isinstance(values, list):
                raise CelLoweringError("'in' needs a constant list")
            if not values:
                return ir.false()
            dtype = _dtype_of_value(values[0])
            return ir.InSet(lhs_path.leaf(dtype), tuple(values), )
        # literal in path-list:  'NET_ADMIN' in c.securityContext.capabilities.add
        rhs_path = self._try_path(node.rhs)
        if rhs_path is not None:
            value = self._const_value(node.lhs)
            dtype = _dtype_of_value(value)
            over = rhs_path.leaf(dtype)
            return ir.AnyOf(
                over=over, pred=ir.Cmp(CmpOp.EQ, ir.Elem((), dtype), Const(value, dtype))
            )
        raise CelLoweringError("'in' needs a field path on one side")

    def _lower_size(self, node: P.Call):
        # size() is polymorphic in CEL (list length, map size, STRING
        # length); CountOf only counts elements, and the operand's runtime
        # type is unknowable statically — a string field would silently
        # count 0. Host interpreter territory.
        raise CelLoweringError("size() needs the host interpreter")

    def _lower_call(self, node: P.Call) -> Expr:
        if node.recv is None:
            if node.name == "has" and len(node.args) == 1:
                path = self._as_path(node.args[0])
                return ir.Exists(path.leaf(DType.ID))
            raise CelLoweringError(f"function {node.name!r} does not lower")
        # string predicate methods
        if node.name in _STR_METHODS:
            if len(node.args) != 1:
                raise CelLoweringError(f"{node.name}() takes one argument")
            pattern = self._const_value(node.args[0])
            if not isinstance(pattern, str):
                raise CelLoweringError(f"{node.name}() needs a string argument")
            path = self._as_path(node.recv)
            return ir.StrPred(
                path.leaf(DType.ID), _STR_METHODS[node.name], pattern
            )
        # macros: list.all(v, pred) / exists / exists_one
        if node.name in ("all", "exists", "exists_one"):
            if len(node.args) != 2 or not isinstance(node.args[0], P.Ident):
                raise CelLoweringError(f"{node.name}() needs (var, predicate)")
            var = node.args[0].name
            domain = self._as_path(node.recv)
            saved = dict(self.env)
            # entering a quantifier: element-relative vars of OUTER scopes
            # cannot be referenced inside (IR has one element scope)
            self.env = {
                name: ref
                for name, ref in self.env.items()
                if ref.kind == "abs"
            }
            self.env[var] = _PathRef("elem", ())
            try:
                pred = self.lower(node.args[1])
            finally:
                self.env = saved
            over = domain.leaf(DType.ID)
            if node.name == "all":
                return ir.AllOf(over=over, pred=pred)
            if node.name == "exists":
                return ir.AnyOf(over=over, pred=pred)
            return ir.Cmp(
                CmpOp.EQ, ir.CountOf(over=over, pred=pred), Const(1, DType.I32)
            )
        raise CelLoweringError(f"method {node.name!r} does not lower")


_FLIPPED = {
    CmpOp.EQ: CmpOp.EQ, CmpOp.NE: CmpOp.NE,
    CmpOp.LT: CmpOp.GT, CmpOp.LE: CmpOp.GE,
    CmpOp.GT: CmpOp.LT, CmpOp.GE: CmpOp.LE,
}


def lower(ast: Any, params: Mapping[str, Any] | None = None) -> Expr:
    """CEL AST → boolean IR expression; raises CelLoweringError when the
    expression is outside the lowerable subset."""
    expr = Lowerer(params or {}).lower(ast)
    ir.typecheck(expr)
    return expr
