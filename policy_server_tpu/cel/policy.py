"""The CEL policy module (PolicyExecutionMode::Cel).

The reference runs CEL policies through a wasm-embedded cel-interpreter
module configured by settings shaped like Kubernetes
ValidatingAdmissionPolicy (src/evaluation/precompiled_policy.rs:46-64;
upstream ghcr.io/kubewarden/policies/cel-policy):

```yaml
settings:
  variables:                      # optional named sub-expressions
    - name: replicas
      expression: "object.spec.replicas"
  validations:                    # at least one; ALL must hold
    - expression: "variables.replicas <= 5"
      message: "too many replicas"
      messageExpression: "'replicas: ' + string(variables.replicas)"
```

TPU-first twist: each validation expression is LOWERED TO PREDICATE IR
(cel/lower.py) so CEL policies run inside the fused device program like
any builtin — no interpreter on the hot path. Expressions outside the
lowerable subset fall back to the host CEL interpreter (cel/interp.py)
for the whole policy, becoming a host-executed policy exactly like a
wasm module. ``variables.<name>`` references are inlined by AST
substitution before lowering, so variables never force the host path.
"""

from __future__ import annotations

from typing import Any, Mapping

from policy_server_tpu.cel import interp as interp_mod
from policy_server_tpu.cel import parser as parser_mod
from policy_server_tpu.cel.interp import CelEvalError
from policy_server_tpu.cel.lower import CelLoweringError, lower
from policy_server_tpu.cel.parser import CelParseError
from policy_server_tpu.context.service import CONTEXT_KEY
from policy_server_tpu.ops import ir
from policy_server_tpu.ops.compiler import PolicyProgram, Rule
from policy_server_tpu.policies.base import (
    BuiltinPolicy,
    SettingsError,
    SettingsValidationResponse,
)


def _substitute_variables(ast: Any, variables: Mapping[str, Any]) -> Any:
    """Replace ``variables.<name>`` selections with the named expression's
    AST (already-substituted, so earlier variables compose)."""
    P = parser_mod
    if isinstance(ast, P.Select):
        if isinstance(ast.base, P.Ident) and ast.base.name == "variables":
            if ast.field not in variables:
                raise SettingsError(f"unknown variable {ast.field!r}")
            return variables[ast.field]
        return P.Select(_substitute_variables(ast.base, variables), ast.field)
    if isinstance(ast, P.Call):
        return P.Call(
            _substitute_variables(ast.recv, variables)
            if ast.recv is not None
            else None,
            ast.name,
            tuple(_substitute_variables(a, variables) for a in ast.args),
        )
    if isinstance(ast, P.Index):
        return P.Index(
            _substitute_variables(ast.base, variables),
            _substitute_variables(ast.index, variables),
        )
    if isinstance(ast, P.Unary):
        return P.Unary(ast.op, _substitute_variables(ast.operand, variables))
    if isinstance(ast, P.Binary):
        return P.Binary(
            ast.op,
            _substitute_variables(ast.lhs, variables),
            _substitute_variables(ast.rhs, variables),
        )
    if isinstance(ast, P.Ternary):
        return P.Ternary(
            _substitute_variables(ast.cond, variables),
            _substitute_variables(ast.then, variables),
            _substitute_variables(ast.other, variables),
        )
    if isinstance(ast, P.ListLit):
        return P.ListLit(
            tuple(_substitute_variables(x, variables) for x in ast.items)
        )
    return ast  # Lit / Ident


def _bindings(payload: Any, settings: Mapping[str, Any]) -> dict[str, Any]:
    """CEL evaluation bindings from one validate payload (the payload root
    IS the AdmissionRequest document, models/admission.py payload())."""
    request = dict(payload) if isinstance(payload, Mapping) else {}
    request.pop(CONTEXT_KEY, None)
    out: dict[str, Any] = {"request": request, "params": dict(settings)}
    if "object" in request:
        out["object"] = request["object"]
    if "oldObject" in request:
        out["oldObject"] = request["oldObject"]
    return out


class _Validation:
    __slots__ = ("ast", "expression", "message", "message_ast")

    def __init__(self, doc: Mapping[str, Any], variables: Mapping[str, Any]):
        if not isinstance(doc, Mapping) or not isinstance(
            doc.get("expression"), str
        ):
            raise SettingsError(
                "each validation needs a string 'expression'"
            )
        self.expression = doc["expression"]
        try:
            self.ast = _substitute_variables(
                parser_mod.parse(self.expression), variables
            )
        except CelParseError as e:
            raise SettingsError(
                f"invalid CEL expression {self.expression!r}: {e}"
            ) from e
        message = doc.get("message")
        if message is not None and not isinstance(message, str):
            raise SettingsError("validation 'message' must be a string")
        self.message = message or f"failed expression: {self.expression}"
        self.message_ast = None
        msg_expr = doc.get("messageExpression")
        if msg_expr is not None:
            if not isinstance(msg_expr, str):
                raise SettingsError(
                    "validation 'messageExpression' must be a string"
                )
            try:
                self.message_ast = _substitute_variables(
                    parser_mod.parse(msg_expr), variables
                )
            except CelParseError as e:
                raise SettingsError(
                    f"invalid messageExpression {msg_expr!r}: {e}"
                ) from e

    def message_for(self, payload: Any, settings: Mapping[str, Any]) -> str:
        if self.message_ast is not None:
            try:
                value = interp_mod.evaluate(
                    self.message_ast, _bindings(payload, settings)
                )
                if isinstance(value, str) and value:
                    return value
            except CelEvalError:
                pass  # fall back to the static message
        return self.message


class CelPolicy(BuiltinPolicy):
    """``builtin://cel-policy`` — Kubernetes-style CEL validations,
    compiled onto the device via predicate-IR lowering with a host
    interpreter fallback."""

    name = "cel-policy"
    mutating = False
    upstream_equivalents = ("ghcr.io/kubewarden/policies/cel-policy",)

    def build(self, settings: Mapping[str, Any]) -> PolicyProgram:
        settings = dict(settings or {})
        validations_doc = settings.get("validations")
        if not isinstance(validations_doc, list) or not validations_doc:
            raise SettingsError(
                "setting 'validations' must be a non-empty list"
            )
        variables: dict[str, Any] = {}
        variables_doc = settings.get("variables") or []
        if not isinstance(variables_doc, list):
            raise SettingsError("setting 'variables' must be a list")
        for v in variables_doc:
            if not isinstance(v, Mapping) or not isinstance(
                v.get("name"), str
            ) or not isinstance(v.get("expression"), str):
                raise SettingsError(
                    "each variable needs string 'name' and 'expression'"
                )
            try:
                variables[v["name"]] = _substitute_variables(
                    parser_mod.parse(v["expression"]), variables
                )
            except CelParseError as e:
                raise SettingsError(
                    f"invalid variable expression {v['expression']!r}: {e}"
                ) from e

        validations = [_Validation(doc, variables) for doc in validations_doc]

        # TPU path: every validation lowers → one deny-rule each (rule
        # fires when the validation does NOT hold)
        rules: list[Rule] = []
        try:
            for i, v in enumerate(validations):
                condition = ir.Not(lower(v.ast, params=settings))
                message: Any = v.message
                if v.message_ast is not None:
                    message = (
                        lambda payload, _v=v: _v.message_for(payload, settings)
                    )
                rules.append(
                    Rule(
                        name=f"cel-validation-{i}",
                        condition=condition,
                        message=message,
                    )
                )
            program = PolicyProgram(rules=tuple(rules))
            program.typecheck()
            return program
        except (CelLoweringError, ir.IRError):
            pass  # outside the lowerable subset → host interpreter

        def host_eval(payload: Any) -> Mapping[str, Any]:
            bindings = _bindings(payload, settings)
            for v in validations:
                try:
                    result = interp_mod.evaluate(v.ast, bindings)
                # host evaluators must NEVER raise (the group member
                # contract, environment._eval_wasm_members): any failure
                # is an in-band deny
                except Exception as e:  # noqa: BLE001
                    return {
                        "accepted": False,
                        "message": f"{v.message} (CEL error: {e})",
                    }
                if result is not True:
                    return {
                        "accepted": False,
                        "message": v.message_for(payload, settings),
                    }
            return {"accepted": True}

        return PolicyProgram(
            rules=(Rule("cel-host-executed", ir.false(), "unreachable"),),
            host_evaluator=host_eval,
        )

    def validate_settings(
        self, settings: Mapping[str, Any]
    ) -> SettingsValidationResponse:
        try:
            self.build(dict(settings or {}))
        except (SettingsError, ValueError) as e:
            return SettingsValidationResponse.error(str(e))
        return SettingsValidationResponse.ok()
