"""Host CEL interpreter — the fallback for expressions outside the
IR-lowerable subset (cel/lower.py) and the engine for
``messageExpression``.

Semantics follow CEL where it matters for validation policies: selecting
a missing field raises :class:`CelEvalError` (a failed validation), the
``all``/``exists``/``exists_one``/``filter``/``map`` macros bind a
variable per element, ``in`` works over lists/maps/strings, and dynamic
values compare by value. Arithmetic, ternaries, and string concatenation
are supported here even though they do not lower to IR.
"""

from __future__ import annotations

import re
from typing import Any, Mapping

from policy_server_tpu.cel import parser as P


class CelEvalError(ValueError):
    pass


class Interpreter:
    def __init__(self, bindings: Mapping[str, Any]):
        self.bindings = dict(bindings)

    def eval(self, node: Any) -> Any:
        if isinstance(node, P.Lit):
            return node.value
        if isinstance(node, P.ListLit):
            return [self.eval(x) for x in node.items]
        if isinstance(node, P.Ident):
            if node.name not in self.bindings:
                raise CelEvalError(f"unknown identifier {node.name!r}")
            return self.bindings[node.name]
        if isinstance(node, P.Select):
            base = self.eval(node.base)
            if isinstance(base, Mapping):
                if node.field not in base:
                    raise CelEvalError(f"no such key: {node.field!r}")
                return base[node.field]
            raise CelEvalError(
                f"cannot select {node.field!r} from {type(base).__name__}"
            )
        if isinstance(node, P.Index):
            base = self.eval(node.base)
            idx = self.eval(node.index)
            try:
                return base[idx]
            except (KeyError, IndexError, TypeError) as e:
                raise CelEvalError(f"bad index: {e}") from e
        if isinstance(node, P.Unary):
            v = self.eval(node.operand)
            if node.op == "!":
                if not isinstance(v, bool):
                    raise CelEvalError("'!' needs a boolean")
                return not v
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise CelEvalError("unary '-' needs a number")
            return -v
        if isinstance(node, P.Ternary):
            cond = self.eval(node.cond)
            if not isinstance(cond, bool):
                raise CelEvalError("ternary condition must be boolean")
            return self.eval(node.then if cond else node.other)
        if isinstance(node, P.Binary):
            return self._binary(node)
        if isinstance(node, P.Call):
            return self._call(node)
        raise CelEvalError(f"unsupported node {type(node).__name__}")

    def _binary(self, node: P.Binary) -> Any:
        op = node.op
        if op == "&&":
            # CEL commutative &&: false short-circuits past errors
            try:
                lhs = self.eval(node.lhs)
            except CelEvalError:
                if self.eval(node.rhs) is False:
                    return False
                raise
            if lhs is False:
                return False
            rhs = self.eval(node.rhs)
            if not isinstance(lhs, bool) or not isinstance(rhs, bool):
                raise CelEvalError("'&&' needs booleans")
            return lhs and rhs
        if op == "||":
            try:
                lhs = self.eval(node.lhs)
            except CelEvalError:
                if self.eval(node.rhs) is True:
                    return True
                raise
            if lhs is True:
                return True
            rhs = self.eval(node.rhs)
            if not isinstance(lhs, bool) or not isinstance(rhs, bool):
                raise CelEvalError("'||' needs booleans")
            return lhs or rhs
        lhs = self.eval(node.lhs)
        rhs = self.eval(node.rhs)
        if op == "in":
            if isinstance(rhs, str):
                if not isinstance(lhs, str):
                    raise CelEvalError("'in' over a string needs a string")
                return lhs in rhs
            if isinstance(rhs, list):
                return any(self._equal(lhs, x) for x in rhs)
            if isinstance(rhs, Mapping):
                try:
                    return lhs in rhs
                except TypeError as e:
                    raise CelEvalError(f"'in' over a map: {e}") from e
            raise CelEvalError("'in' needs a list, map, or string")
        if op in ("==", "!="):
            eq = self._equal(lhs, rhs)
            return eq if op == "==" else not eq
        if op in ("<", "<=", ">", ">="):
            if not self._ordered(lhs, rhs):
                raise CelEvalError(f"cannot order {lhs!r} and {rhs!r}")
            return {
                "<": lhs < rhs, "<=": lhs <= rhs,
                ">": lhs > rhs, ">=": lhs >= rhs,
            }[op]
        if op == "+":
            if isinstance(lhs, str) and isinstance(rhs, str):
                return lhs + rhs
            if isinstance(lhs, list) and isinstance(rhs, list):
                return lhs + rhs
            return self._arith(lhs, rhs, lambda a, b: a + b)
        if op == "-":
            return self._arith(lhs, rhs, lambda a, b: a - b)
        if op == "*":
            return self._arith(lhs, rhs, lambda a, b: a * b)
        if op == "/":
            if rhs == 0:
                raise CelEvalError("division by zero")
            if isinstance(lhs, int) and isinstance(rhs, int):
                return self._arith(lhs, rhs, lambda a, b: int(a / b))
            return self._arith(lhs, rhs, lambda a, b: a / b)
        if op == "%":
            if rhs == 0:
                raise CelEvalError("modulo by zero")
            return self._arith(lhs, rhs, lambda a, b: a - int(a / b) * b)
        raise CelEvalError(f"unsupported operator {op!r}")

    @staticmethod
    def _equal(a: Any, b: Any) -> bool:
        if isinstance(a, bool) != isinstance(b, bool):
            return False
        return a == b

    @staticmethod
    def _ordered(a: Any, b: Any) -> bool:
        if isinstance(a, bool) or isinstance(b, bool):
            return False
        if isinstance(a, (int, float)) and isinstance(b, (int, float)):
            return True
        return isinstance(a, str) and isinstance(b, str)

    @staticmethod
    def _arith(a: Any, b: Any, fn) -> Any:
        if isinstance(a, bool) or isinstance(b, bool):
            raise CelEvalError("arithmetic on booleans")
        if not isinstance(a, (int, float)) or not isinstance(b, (int, float)):
            raise CelEvalError("arithmetic needs numbers")
        return fn(a, b)

    def _call(self, node: P.Call) -> Any:
        name = node.name
        if node.recv is None:
            if name == "has":
                if len(node.args) != 1 or not isinstance(
                    node.args[0], (P.Select, P.Index)
                ):
                    raise CelEvalError("has() needs a field selection")
                try:
                    self.eval(node.args[0])
                    return True
                except CelEvalError:
                    return False
            if name == "size":
                (arg,) = node.args
                v = self.eval(arg)
                if isinstance(v, (str, list, Mapping)):
                    return len(v)
                raise CelEvalError("size() needs a string, list, or map")
            if name in ("int", "double", "string"):
                (arg,) = node.args
                v = self.eval(arg)
                try:
                    if name == "int":
                        return int(v)
                    if name == "double":
                        return float(v)
                    return v if isinstance(v, str) else _to_string(v)
                except (TypeError, ValueError) as e:
                    raise CelEvalError(f"{name}(): {e}") from e
            raise CelEvalError(f"unknown function {name!r}")
        recv = self.eval(node.recv)
        if name in ("all", "exists", "exists_one", "filter", "map"):
            return self._macro(name, recv, node.args)
        if name in ("contains", "startsWith", "endsWith", "matches"):
            (arg,) = node.args
            pattern = self.eval(arg)
            if not isinstance(recv, str) or not isinstance(pattern, str):
                raise CelEvalError(f"{name}() needs strings")
            if name == "contains":
                return pattern in recv
            if name == "startsWith":
                return recv.startswith(pattern)
            if name == "endsWith":
                return recv.endswith(pattern)
            try:
                return re.search(pattern, recv) is not None
            except re.error as e:
                raise CelEvalError(f"matches(): bad pattern: {e}") from e
        raise CelEvalError(f"unknown method {name!r}")

    def _macro(self, name: str, recv: Any, args: tuple) -> Any:
        if len(args) != 2 or not isinstance(args[0], P.Ident):
            raise CelEvalError(f"{name}() needs (var, expression)")
        var = args[0].name
        if isinstance(recv, Mapping):
            elements: list = list(recv.keys())
        elif isinstance(recv, list):
            elements = recv
        else:
            raise CelEvalError(f"{name}() needs a list or map")
        saved = self.bindings.get(var, _MISSING)
        results = []
        try:
            for elem in elements:
                self.bindings[var] = elem
                results.append(self.eval(args[1]))
        finally:
            if saved is _MISSING:
                self.bindings.pop(var, None)
            else:
                self.bindings[var] = saved
        if name in ("all", "exists", "exists_one"):
            if not all(isinstance(r, bool) for r in results):
                raise CelEvalError(f"{name}() predicate must be boolean")
            if name == "all":
                return all(results)
            if name == "exists":
                return any(results)
            return sum(results) == 1
        if name == "filter":
            return [e for e, r in zip(elements, results) if r is True]
        return results  # map


_MISSING = object()


def _to_string(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return "null"
    return str(v)


def evaluate(ast: Any, bindings: Mapping[str, Any]) -> Any:
    return Interpreter(bindings).eval(ast)
