"""CEL expression parser (the subset Kubernetes-style validation
expressions use).

Grammar (CEL spec precedence):
  ternary   :  or ('?' or ':' ternary)?
  or        :  and ('||' and)*
  and       :  rel ('&&' rel)*
  rel       :  add (('=='|'!='|'<'|'<='|'>'|'>='|'in') add)?
  add       :  mul (('+'|'-') mul)*
  mul       :  unary (('*'|'/'|'%') unary)*
  unary     :  ('!'|'-')* postfix
  postfix   :  primary ('.' IDENT ('(' args ')')? | '[' ternary ']')*
  primary   :  literal | IDENT ('(' args ')')? | '(' ternary ')' | list

Produces a small AST (dataclasses below) consumed by lower.py (→ IR for
the fused device program) and interp.py (host evaluation fallback).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any


class CelParseError(ValueError):
    pass


# -- AST --------------------------------------------------------------------


@dataclass(frozen=True)
class Lit:
    value: Any


@dataclass(frozen=True)
class Ident:
    name: str


@dataclass(frozen=True)
class Select:
    base: Any
    field: str


@dataclass(frozen=True)
class Call:
    recv: Any  # None for global functions (size, has, ...)
    name: str
    args: tuple


@dataclass(frozen=True)
class Index:
    base: Any
    index: Any


@dataclass(frozen=True)
class Unary:
    op: str  # '!' | '-'
    operand: Any


@dataclass(frozen=True)
class Binary:
    op: str  # '||' '&&' '==' '!=' '<' '<=' '>' '>=' 'in' '+' '-' '*' '/' '%'
    lhs: Any
    rhs: Any


@dataclass(frozen=True)
class Ternary:
    cond: Any
    then: Any
    other: Any


@dataclass(frozen=True)
class ListLit:
    items: tuple


# -- tokenizer --------------------------------------------------------------

_TWO_CHAR = {"==", "!=", "<=", ">=", "&&", "||"}
_ONE_CHAR = set("()[]{},.?:!<>-+*/%")


def _tokenize(src: str) -> list[tuple[str, Any]]:
    out: list[tuple[str, Any]] = []
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        if c.isspace():
            i += 1
            continue
        if src[i : i + 2] in _TWO_CHAR:
            out.append(("op", src[i : i + 2]))
            i += 2
            continue
        if c in ("'", '"'):
            j = i + 1
            buf: list[str] = []
            while j < n and src[j] != c:
                if src[j] == "\\" and j + 1 < n:
                    esc = src[j + 1]
                    buf.append(
                        {"n": "\n", "t": "\t", "r": "\r"}.get(esc, esc)
                    )
                    j += 2
                else:
                    buf.append(src[j])
                    j += 1
            if j >= n:
                raise CelParseError(f"unterminated string at {i}")
            out.append(("str", "".join(buf)))
            i = j + 1
            continue
        if c.isdigit():
            j = i
            while j < n and (src[j].isdigit() or src[j] == "."):
                j += 1
            text = src[i:j]
            if text.count(".") > 1:
                raise CelParseError(f"bad number {text!r}")
            out.append(("num", float(text) if "." in text else int(text)))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (src[j].isalnum() or src[j] == "_"):
                j += 1
            word = src[i:j]
            if word in ("true", "false"):
                out.append(("bool", word == "true"))
            elif word == "null":
                out.append(("null", None))
            elif word == "in":
                out.append(("op", "in"))
            else:
                out.append(("ident", word))
            i = j
            continue
        if c in _ONE_CHAR:
            out.append(("op", c))
            i += 1
            continue
        raise CelParseError(f"unexpected character {c!r} at {i}")
    return out


# -- parser -----------------------------------------------------------------


class _Parser:
    def __init__(self, tokens: list[tuple[str, Any]]):
        self.toks = tokens
        self.pos = 0

    def peek(self) -> tuple[str, Any] | None:
        return self.toks[self.pos] if self.pos < len(self.toks) else None

    def next(self) -> tuple[str, Any]:
        tok = self.peek()
        if tok is None:
            raise CelParseError("unexpected end of expression")
        self.pos += 1
        return tok

    def expect(self, op: str) -> None:
        tok = self.next()
        if tok != ("op", op):
            raise CelParseError(f"expected {op!r}, got {tok!r}")

    def at_op(self, *ops: str) -> str | None:
        tok = self.peek()
        if tok and tok[0] == "op" and tok[1] in ops:
            return tok[1]
        return None

    # -- grammar ------------------------------------------------------------

    def ternary(self):
        cond = self.or_()
        if self.at_op("?"):
            self.next()
            then = self.or_()
            self.expect(":")
            other = self.ternary()
            return Ternary(cond, then, other)
        return cond

    def or_(self):
        node = self.and_()
        while self.at_op("||"):
            self.next()
            node = Binary("||", node, self.and_())
        return node

    def and_(self):
        node = self.rel()
        while self.at_op("&&"):
            self.next()
            node = Binary("&&", node, self.rel())
        return node

    def rel(self):
        node = self.add()
        op = self.at_op("==", "!=", "<", "<=", ">", ">=", "in")
        if op:
            self.next()
            node = Binary(op, node, self.add())
        return node

    def add(self):
        node = self.mul()
        while True:
            op = self.at_op("+", "-")
            if not op:
                return node
            self.next()
            node = Binary(op, node, self.mul())

    def mul(self):
        node = self.unary()
        while True:
            op = self.at_op("*", "/", "%")
            if not op:
                return node
            self.next()
            node = Binary(op, node, self.unary())

    def unary(self):
        op = self.at_op("!", "-")
        if op:
            self.next()
            return Unary(op, self.unary())
        return self.postfix()

    def postfix(self):
        node = self.primary()
        while True:
            if self.at_op("."):
                self.next()
                kind, name = self.next()
                if kind != "ident":
                    raise CelParseError(f"expected field name, got {name!r}")
                if self.at_op("("):
                    node = Call(node, name, self.args())
                else:
                    node = Select(node, name)
            elif self.at_op("["):
                self.next()
                idx = self.ternary()
                self.expect("]")
                node = Index(node, idx)
            else:
                return node

    def args(self) -> tuple:
        self.expect("(")
        items = []
        if not self.at_op(")"):
            items.append(self.ternary())
            while self.at_op(","):
                self.next()
                items.append(self.ternary())
        self.expect(")")
        return tuple(items)

    def primary(self):
        tok = self.next()
        kind, value = tok
        if kind in ("str", "num", "bool", "null"):
            return Lit(value)
        if kind == "ident":
            if self.at_op("("):
                return Call(None, value, self.args())
            return Ident(value)
        if tok == ("op", "("):
            node = self.ternary()
            self.expect(")")
            return node
        if tok == ("op", "["):
            items = []
            if not self.at_op("]"):
                items.append(self.ternary())
                while self.at_op(","):
                    self.next()
                    items.append(self.ternary())
            self.expect("]")
            return ListLit(tuple(items))
        raise CelParseError(f"unexpected token {tok!r}")


def parse(src: str):
    """CEL source → AST; raises CelParseError."""
    if not isinstance(src, str) or not src.strip():
        raise CelParseError("empty expression")
    parser = _Parser(_tokenize(src))
    node = parser.ternary()
    if parser.peek() is not None:
        raise CelParseError(f"trailing tokens from {parser.peek()!r}")
    return node
