"""CEL execution mode (SURVEY.md §2.2 PolicyExecutionMode::Cel).

Parser → IR lowering (device fast path) → host interpreter (fallback);
policy module in cel/policy.py, registered as ``builtin://cel-policy``.
"""

from policy_server_tpu.cel.interp import CelEvalError, evaluate
from policy_server_tpu.cel.lower import CelLoweringError, lower
from policy_server_tpu.cel.parser import CelParseError, parse
from policy_server_tpu.cel.policy import CelPolicy

__all__ = [
    "CelEvalError",
    "CelLoweringError",
    "CelParseError",
    "CelPolicy",
    "evaluate",
    "lower",
    "parse",
]
