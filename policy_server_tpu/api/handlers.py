"""HTTP handlers.

Reference parity: src/api/handlers.rs —
* POST ``/validate/{policy_id}``     → validate_handler (handlers.rs:120-141)
* POST ``/validate_raw/{policy_id}`` → validate_raw_handler (143-174)
* POST ``/audit/{policy_id}``        → audit_handler (69-90)
* GET  ``/readiness``                → readiness_handler (176-178)
* GET  ``/debug/pprof/cpu|heap``     → pprof handlers (193-254)
* error mapping: PolicyNotFound → 404, everything else → 500
  "Something went wrong" (321-342); malformed JSON body → 422 ApiError
  (JsonExtractor, 30-39).

Request spans carry the reference's field set (request_uid, host, policy_id,
resource identifiers, allowed/mutated/response_*, handlers.rs:46-67 and
288-319). Evaluation itself goes through the micro-batcher: the await on the
batcher future is the analog of `acquire_semaphore_and_evaluate`'s
semaphore + spawn_blocking hop (handlers.rs:256-286)."""

from __future__ import annotations

import asyncio
import json

from aiohttp import web

from policy_server_tpu.api import profiling
from policy_server_tpu.api.api_error import (
    api_error,
    json_body_error,
    something_went_wrong,
)
from policy_server_tpu.api.service import RequestOrigin
from policy_server_tpu.api.state import ApiServerState
from policy_server_tpu.evaluation.errors import (
    EvaluationError,
    PolicyNotFoundError,
)
from policy_server_tpu.runtime.batcher import ShedError
from policy_server_tpu.models import (
    AdmissionResponse,
    AdmissionReviewRequest,
    AdmissionReviewResponse,
    RawReviewRequest,
    RawReviewResponse,
    ValidateRequest,
)
from policy_server_tpu.telemetry import default_registry
from policy_server_tpu.telemetry.tracing import logger, span

STATE_KEY = web.AppKey("state", ApiServerState)

# one request-body cap for EVERY process that can accept the API socket
# (in-process app and prefork workers must agree or limits go
# nondeterministic behind SO_REUSEPORT)
MAX_BODY_BYTES = 8 * 1024**2


class BodyError(Exception):
    """Malformed request body; ``message`` carries the 422 text."""

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


def parse_admission_review_bytes(body: bytes) -> AdmissionReviewRequest:
    """The ONE parse+error contract for admission review bodies, shared by
    the in-process handlers, the prefork workers, and the evaluation
    bridge (a 422 body must not depend on which process parsed it)."""
    try:
        return AdmissionReviewRequest.from_dict(json.loads(body))
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise BodyError(f"Failed to parse the request body as JSON: {e}") from e
    except (KeyError, TypeError, ValueError, AttributeError) as e:
        raise BodyError(f"Failed to deserialize the JSON body: {e}") from e


def _span_fields_from_admission(review: AdmissionReviewRequest) -> dict:
    """populate_span_with_admission_request_data (handlers.rs:288-306)."""
    req = review.request
    fields = {
        "request_uid": req.uid,
        "name": req.name,
        "namespace": req.namespace,
        "operation": req.operation,
        "subresource": req.sub_resource,
    }
    if req.kind:
        fields.update(
            kind_group=req.kind.group, kind_version=req.kind.version,
            kind=req.kind.kind,
        )
    if req.resource:
        fields.update(
            resource_group=req.resource.group,
            resource_version=req.resource.version,
            resource=req.resource.resource,
        )
    return {k: v for k, v in fields.items() if v not in (None, "")}


def _record_response(fields: dict, response: AdmissionResponse) -> None:
    """populate_span_with_policy_evaluation_results (handlers.rs:308-319)."""
    fields["allowed"] = response.allowed
    fields["mutated"] = response.patch is not None
    if response.status:
        if response.status.code is not None:
            fields["response_code"] = response.status.code
        if response.status.message:
            fields["response_message"] = response.status.message


def _tenant_span_field(request: web.Request) -> dict:
    """``{"tenant": name}`` for tenant-routed requests; empty for the
    default routes so their span/log lines stay byte-identical."""
    name = request.match_info.get("tenant")
    return {} if name is None else {"tenant": name}


def _incoming_trace(request: web.Request):
    """The W3C ``traceparent`` parent of this request, when the span
    pipeline is installed (round 18): webhook-originated traces then
    correlate end-to-end instead of starting fresh roots. None keeps
    the historical fresh-root behavior (and skips the header parse
    entirely when no tracer exists)."""
    from policy_server_tpu.telemetry import otlp

    if otlp.tracer() is None:
        return None
    return otlp.parse_traceparent(request.headers.get("traceparent"))


def _tenant_state(state: ApiServerState, request: web.Request):
    """Resolve the serving tenant from the request path (round 16,
    tenancy.py): un-prefixed routes keep the default epoch pointer (the
    state itself — every existing URL unchanged); ``{tenant}`` routes
    resolve through the tenant registry. Returns ``(state_like, None)``
    or ``(None, 404 response)`` for an unknown tenant."""
    name = request.match_info.get("tenant")
    if name is None:
        return state, None
    from policy_server_tpu.tenancy import (
        lookup_tenant,
        unknown_tenant_message,
    )

    tenant = lookup_tenant(state, name)
    if tenant is None:
        return None, api_error(404, unknown_tenant_message(name))
    return tenant.state, None


async def _evaluate(
    batcher,
    policy_id: str,
    request: ValidateRequest,
    origin: RequestOrigin,
) -> AdmissionResponse | web.Response:
    """Dispatch through the batcher; map EvaluationError → ApiError
    responses (handlers.rs:321-342)."""
    try:
        # submit_async returns a loop-bound asyncio future; whole batches
        # deliver with one loop wakeup (runtime/batcher.py _DeliveryBatch)
        future = await batcher.submit_async(policy_id, request, origin)
        return await future
    except ShedError as e:
        # admission-time load shed (429) or shard fence (503, FencedError
        # subclass): either way the row cannot be answered with a verdict
        # now, and an HTTP error with Retry-After beats evaluating work
        # the API server will time out anyway. Status and message come
        # off the exception class so both surfaces stay byte-identical
        # with the native frontend's _shed_body.
        import math as _math

        retry_after = max(1, _math.ceil(e.retry_after_seconds))
        return web.json_response(
            {
                "message": getattr(
                    e, "message", "policy server overloaded; retry later"
                ),
                "retry_after_seconds": retry_after,
            },
            status=getattr(e, "http_status", 429),
            headers={"Retry-After": str(retry_after)},
        )
    except PolicyNotFoundError as e:
        return api_error(404, str(e))
    except EvaluationError as e:
        logger.error("Evaluation error: %s", e)
        return something_went_wrong()
    except Exception as e:  # noqa: BLE001 — keep the JSON error contract
        logger.error("Evaluation error: %s", e)
        return something_went_wrong()


async def _read_admission_review(
    request: web.Request,
) -> AdmissionReviewRequest | web.Response:
    try:
        return parse_admission_review_bytes(await request.read())
    except BodyError as e:
        return json_body_error(e.message)


async def validate_handler(request: web.Request) -> web.Response:
    state = request.app[STATE_KEY]
    policy_id = request.match_info["policy_id"]
    tstate, denied = _tenant_state(state, request)
    if denied is not None:
        return denied
    review = await _read_admission_review(request)
    if isinstance(review, web.Response):
        return review
    with span(
        "validation", parent_ctx=_incoming_trace(request),
        host=state.hostname, policy_id=policy_id,
        **_tenant_span_field(request),
        **_span_fields_from_admission(review),
    ) as fields:
        result = await _evaluate(
            tstate.batcher, policy_id,
            ValidateRequest.from_admission(review.request),
            RequestOrigin.VALIDATE,
        )
        if isinstance(result, web.Response):
            return result
        _record_response(fields, result)
        return web.json_response(AdmissionReviewResponse(result).to_dict())


async def audit_handler(request: web.Request) -> web.Response:
    state = request.app[STATE_KEY]
    policy_id = request.match_info["policy_id"]
    tstate, denied = _tenant_state(state, request)
    if denied is not None:
        return denied
    review = await _read_admission_review(request)
    if isinstance(review, web.Response):
        return review
    with span(
        "audit", parent_ctx=_incoming_trace(request),
        host=state.hostname, policy_id=policy_id,
        **_tenant_span_field(request),
        **_span_fields_from_admission(review),
    ) as fields:
        result = await _evaluate(
            tstate.batcher, policy_id,
            ValidateRequest.from_admission(review.request),
            RequestOrigin.AUDIT,
        )
        if isinstance(result, web.Response):
            return result
        _record_response(fields, result)
        return web.json_response(AdmissionReviewResponse(result).to_dict())


def _audit_reports_etag(state: ApiServerState) -> str:
    """The GET /audit/reports validator: snapshot generation (what the
    cluster looks like) + serving epoch (which policy set judged it) +
    report-store version (what the sweeps actually wrote). Any change an
    unchanged-ETag response could hide bumps one of the three."""
    scanner = state.audit
    generation = scanner.snapshot.stats().get("generation", 0)
    epoch = (
        state.lifecycle.current_epoch if state.lifecycle is not None else 0
    )
    return f'"audit-{generation}-{epoch}-{scanner.reports.version()}"'


async def audit_reports_handler(request: web.Request) -> web.Response:
    """GET /audit/reports[/{namespace}] — the background audit scanner's
    PolicyReport-style output (round 10): per-resource × per-policy raw
    verdicts stamped with the policy epoch that produced them, plus
    summary counters and scanner freshness. 404 when --audit-mode off.
    Round 23: carries an ETag and honors If-None-Match with 304, so
    pollers that have not migrated to /audit/stream stop re-serializing
    unchanged full reports."""
    state = request.app[STATE_KEY]
    if state.audit is None:
        return api_error(404, "the background audit scanner is disabled")
    namespace = request.match_info.get("namespace")
    etag = _audit_reports_etag(state)
    if request.headers.get("If-None-Match") == etag:
        return web.Response(status=304, headers={"ETag": etag})
    return web.json_response(
        state.audit.report_payload(namespace), headers={"ETag": etag}
    )


async def audit_stream_handler(request: web.Request) -> web.StreamResponse:
    """GET /audit/stream[?cursor=N] — the verdict matrix's watch-style
    changelog as chunked JSON lines (round 23). Each line carries a
    monotonic ``matrixVersion``; a client that disconnects resumes with
    ``?cursor=<last seen>`` and replays exactly the missed entries, or
    gets a RESYNC marker + full state when the ring no longer covers the
    cursor. A slow consumer overflows its own bounded queue and is
    dropped with a counted close — the sweep applier never blocks on a
    client. 404 without --audit-matrix; 503 over the client cap."""
    state = request.app[STATE_KEY]
    matrix = state.audit_matrix
    if matrix is None:
        return api_error(404, "the verdict matrix is disabled")
    if matrix.stream_clients() >= state.audit_stream_max_clients:
        return api_error(
            503,
            f"audit stream client cap reached "
            f"({state.audit_stream_max_clients}); retry later",
        )
    cursor: int | None = None
    raw_cursor = request.query.get("cursor")
    if raw_cursor is not None:
        try:
            cursor = int(raw_cursor)
        except ValueError:
            return api_error(422, f"invalid cursor {raw_cursor!r}")
    resp = web.StreamResponse(
        status=200,
        headers={
            "Content-Type": "application/x-ndjson",
            "Cache-Control": "no-cache",
        },
    )
    resp.enable_chunked_encoding()
    await resp.prepare(request)
    sub = matrix.subscribe(cursor)
    try:
        while True:
            entries, dead = matrix.drain(sub)
            for entry in entries:
                await resp.write(
                    json.dumps(entry, separators=(",", ":")).encode()
                    + b"\n"
                )
            if dead:
                # the queue overflowed while we were writing: tell the
                # client honestly (it reconnects with its cursor) and
                # close — a silent gap would corrupt its matrix view
                await resp.write(
                    json.dumps(
                        {
                            "type": "OVERFLOW",
                            "matrixVersion": matrix.version,
                        },
                        separators=(",", ":"),
                    ).encode() + b"\n"
                )
                break
            await asyncio.sleep(0.1)
    except (ConnectionResetError, asyncio.CancelledError):
        pass  # client went away — the cursor contract covers its return
    finally:
        matrix.unsubscribe(sub)
    return resp


async def validate_raw_handler(request: web.Request) -> web.Response:
    state = request.app[STATE_KEY]
    policy_id = request.match_info["policy_id"]
    tstate, denied = _tenant_state(state, request)
    if denied is not None:
        return denied
    try:
        body = json.loads(await request.read())
        raw_review = RawReviewRequest.from_dict(body)
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        return json_body_error(f"Failed to parse the request body as JSON: {e}")
    except (KeyError, TypeError, ValueError) as e:
        return json_body_error(f"Failed to deserialize the JSON body: {e}")
    with span(
        "validation_raw", parent_ctx=_incoming_trace(request),
        host=state.hostname, policy_id=policy_id,
        **_tenant_span_field(request),
    ) as fields:
        result = await _evaluate(
            tstate.batcher, policy_id,
            ValidateRequest.from_raw(raw_review.request),
            RequestOrigin.VALIDATE,
        )
        if isinstance(result, web.Response):
            return result
        _record_response(fields, result)
        return web.json_response(RawReviewResponse(result).to_dict())


async def readiness_handler(request: web.Request) -> web.Response:
    """Honest readiness (round 9): 503 until the first policy epoch is
    compiled+warmed, 200 on last-good during a background reload, 503
    when every shard's breaker is open under --degraded-mode reject
    (ApiServerState.readiness holds the verdict logic; multi-tenant
    deployments aggregate — 503 only when EVERY tenant is degraded)."""
    status, text = request.app[STATE_KEY].readiness()
    return web.Response(status=status, text=text)


async def readiness_tenant_handler(request: web.Request) -> web.Response:
    """GET /readiness/{tenant} (round 16): ONE tenant's honest verdict —
    503 until that tenant's first epoch is compiled+warmed, or while its
    breakers are all open under a per-tenant --degraded-mode reject.
    404 for unknown tenants (and for every name when no tenants
    manifest is configured)."""
    state = request.app[STATE_KEY]
    name = request.match_info["tenant"]
    from policy_server_tpu.tenancy import (
        lookup_tenant,
        unknown_tenant_message,
    )

    tenant = lookup_tenant(state, name)
    if tenant is None:
        return api_error(404, unknown_tenant_message(name))
    status, text = tenant.readiness()
    return web.Response(status=status, text=text)


# -- policy-lifecycle admin endpoints (lifecycle.py) ------------------------


def _admin_gate(state: ApiServerState, request: web.Request) -> web.Response | None:
    """Auth for the /policies/* admin endpoints: a bearer token must be
    CONFIGURED (--reload-admin-token) and presented. Serving on the
    plaintext readiness port keeps the surface cluster-internal like
    /metrics; the token keeps it operator-only."""
    if state.lifecycle is None:
        return api_error(404, "policy hot reload is disabled")
    if not state.admin_token:
        return api_error(
            403,
            "policy admin endpoints disabled: --reload-admin-token is "
            "not configured",
        )
    header = request.headers.get("Authorization", "")
    import hmac

    expected = f"Bearer {state.admin_token}"
    if not hmac.compare_digest(header, expected):
        return api_error(401, "invalid or missing bearer token")
    return None


async def policies_reload_handler(request: web.Request) -> web.Response:
    state = request.app[STATE_KEY]
    denied = _admin_gate(state, request)
    if denied is not None:
        return denied
    started = state.lifecycle.request_reload("admin-endpoint")
    body = {
        "status": "reload started" if started else
        "reload already in progress",
        "epoch": state.lifecycle.current_epoch,
    }
    # last shadow-canary cluster what-if (round 23): the verdict flips
    # the PREVIOUS candidate would have caused — this reload's own diff
    # lands once its canary runs (poll this endpoint or the matrix)
    whatif = state.lifecycle.stats().get("whatif")
    if whatif is not None:
        body["whatif"] = whatif
    return web.json_response(body, status=202)


async def _lifecycle_action(
    request: web.Request, action: str
) -> web.Response:
    """Shared body for the synchronous promote/rollback endpoints."""
    from policy_server_tpu.lifecycle import ReloadRejected

    state = request.app[STATE_KEY]
    denied = _admin_gate(state, request)
    if denied is not None:
        return denied
    fn = getattr(state.lifecycle, action)
    try:
        # promote/rollback build + start a batcher: off the event loop
        outcome = await asyncio.get_running_loop().run_in_executor(None, fn)
    except ReloadRejected as e:
        return api_error(409, str(e))
    except Exception as e:  # noqa: BLE001 — keep the JSON error contract
        logger.error("policy %s failed: %s", action, e)
        return something_went_wrong()
    body = {"status": outcome, "epoch": state.lifecycle.current_epoch}
    whatif = state.lifecycle.stats().get("whatif")
    if whatif is not None:
        body["whatif"] = whatif
    return web.json_response(body)


async def policies_rollback_handler(request: web.Request) -> web.Response:
    return await _lifecycle_action(request, "rollback")


async def policies_promote_handler(request: web.Request) -> web.Response:
    return await _lifecycle_action(request, "promote_staged")


async def metrics_handler(request: web.Request) -> web.Response:
    """Prometheus exposition (this build's pull-based replacement for the
    reference's OTLP push, see telemetry/metrics.py). Serving-runtime
    introspection (dispatch counts, watchdog abandonments, queue depth,
    oracle fallbacks) rides the same registry via the runtime-stats
    collector the server attaches at bootstrap."""
    return web.Response(
        body=default_registry().exposition(),
        content_type="text/plain",
        charset="utf-8",
    )


async def timeline_handler(request: web.Request) -> web.Response:
    """GET /debug/timeline (round 18): the flight recorder's ring as
    Chrome/Perfetto trace JSON — batch phase tracks, native-frontend
    burst aggregates, sampled-row tracks, plus the current tail
    exemplars and ring accounting under ``otherData``. Load the body in
    https://ui.perfetto.dev or chrome://tracing. 404 when
    --flight-recorder off. Served on the readiness port (always the
    main process, cluster-internal like /metrics) and on the
    python-frontend API port."""
    from policy_server_tpu.telemetry import flightrec

    rec = flightrec.recorder()
    if rec is None:
        return api_error(404, "the flight recorder is disabled")
    # snapshot + JSON render walk the whole ring: off the event loop
    body = await asyncio.get_running_loop().run_in_executor(
        None, rec.chrome_trace_json
    )
    return web.Response(body=body, content_type="application/json")


async def pprof_cpu_handler(request: web.Request) -> web.Response:
    """GET /debug/pprof/cpu?interval= (handlers.rs:193-223). Interval is
    seconds (default 30, profiling.rs:48-51); runs off the event loop."""
    try:
        interval = float(
            request.query.get("interval", profiling.DEFAULT_PROFILING_INTERVAL)
        )
        frequency = int(
            request.query.get("frequency", profiling.DEFAULT_PROFILING_FREQUENCY)
        )
    except ValueError:
        return json_body_error("invalid 'interval'/'frequency' query parameter")
    try:
        profile = await asyncio.get_running_loop().run_in_executor(
            None, profiling.start_one_cpu_profile, interval, frequency
        )
    except profiling.ProfileInProgress as e:
        return api_error(409, str(e))
    except Exception as e:  # noqa: BLE001
        logger.error("pprof error: %s", e)
        return something_went_wrong()
    return web.Response(
        body=profile.text.encode(),
        content_type="application/octet-stream",
        headers={"Content-Disposition": 'attachment; filename="cpu.pprof.txt"'},
    )


async def pprof_heap_handler(request: web.Request) -> web.Response:
    """GET /debug/pprof/heap (handlers.rs:227-254): host allocations +
    device HBM stats."""
    try:
        body = await asyncio.get_running_loop().run_in_executor(
            None, profiling.heap_profile
        )
    except Exception as e:  # noqa: BLE001
        logger.error("pprof error: %s", e)
        return something_went_wrong()
    return web.Response(body=body, content_type="application/json")


def build_router(state: ApiServerState) -> web.Application:
    """The API application (reference router wiring, src/lib.rs:205-225)."""
    app = web.Application(client_max_size=MAX_BODY_BYTES)
    app[STATE_KEY] = state
    app.router.add_post("/validate/{policy_id}", validate_handler)
    app.router.add_post("/validate_raw/{policy_id}", validate_raw_handler)
    # literal /audit/reports routes BEFORE the /audit/{policy_id}
    # wildcard so the report listing wins path resolution
    app.router.add_get("/audit/reports", audit_reports_handler)
    app.router.add_get("/audit/reports/{namespace}", audit_reports_handler)
    # verdict-matrix changelog stream (round 23) — literal, same
    # wildcard-shadowing rule as /audit/reports ('stream' is reserved)
    app.router.add_get("/audit/stream", audit_stream_handler)
    app.router.add_post("/audit/{policy_id}", audit_handler)
    # tenant-routed evaluation surface (round 16, tenancy.py): the
    # tenant rides the path; the un-prefixed routes above stay the
    # reserved default tenant. 'reports' is a reserved tenant name, so
    # the literal audit routes can never be shadowed.
    app.router.add_post(
        "/validate/{tenant}/{policy_id}", validate_handler
    )
    app.router.add_post(
        "/validate_raw/{tenant}/{policy_id}", validate_raw_handler
    )
    app.router.add_post("/audit/{tenant}/{policy_id}", audit_handler)
    if state.enable_pprof:
        app.router.add_get("/debug/pprof/cpu", pprof_cpu_handler)
        app.router.add_get("/debug/pprof/heap", pprof_heap_handler)
    # flight-recorder timeline (round 18): also on the API port for the
    # python frontend (the native frontend serves only the evaluation
    # POSTs; the readiness-port copy below is the always-there surface)
    app.router.add_get("/debug/timeline", timeline_handler)
    return app


def build_readiness_router(state: ApiServerState) -> web.Application:
    """The plaintext readiness application (lib.rs:225, cli.rs:71-76) —
    also exposes /metrics (Prometheus pull)."""
    app = web.Application()
    app[STATE_KEY] = state
    app.router.add_get("/readiness", readiness_handler)
    # per-tenant honest readiness (round 16): 503 until THAT tenant's
    # first epoch is warmed / while it is degraded-rejecting
    app.router.add_get("/readiness/{tenant}", readiness_tenant_handler)
    app.router.add_get("/metrics", metrics_handler)
    # policy-lifecycle admin surface (bearer-token gated; 404 when the
    # lifecycle manager is absent, 403 when no token is configured)
    app.router.add_post("/policies/reload", policies_reload_handler)
    app.router.add_post("/policies/promote", policies_promote_handler)
    app.router.add_post("/policies/rollback", policies_rollback_handler)
    # audit reports ALSO on the readiness port: always served by the
    # main process (prefork workers only proxy the validate/audit POST
    # surface), cluster-internal like /metrics
    app.router.add_get("/audit/reports", audit_reports_handler)
    app.router.add_get("/audit/reports/{namespace}", audit_reports_handler)
    # verdict-matrix changelog stream: also on the readiness port (the
    # main process owns the matrix; prefork workers only proxy POSTs)
    app.router.add_get("/audit/stream", audit_stream_handler)
    # flight-recorder timeline (round 18): the main-process ring is the
    # one with the batcher/device phases, and the readiness port is
    # always served by the main process — the canonical surface
    app.router.add_get("/debug/timeline", timeline_handler)
    return app
