"""JSON API errors (reference src/api/api_error.rs:7-30: ``ApiError{status,
message}`` rendered as ``{"message": ..., "status": ...}``)."""

from __future__ import annotations

import json

from aiohttp import web


def api_error(status: int, message: str) -> web.Response:
    return web.json_response(
        {"message": message, "status": status}, status=status
    )


def api_error_body(status: int, message: str) -> bytes:
    """The serialized :func:`api_error` body — the ONE spelling of the
    byte-parity-critical error shape for surfaces that frame their own
    HTTP (the native frontend's C++ loops, the prefork bridge)."""
    return json.dumps({"message": message, "status": status}).encode()


def json_body_error(message: str) -> web.Response:
    """Malformed/undeserializable JSON body → 422 (the axum JsonRejection
    path, src/api/handlers.rs:30-39; integration_test.rs:155-172 expects
    UNPROCESSABLE_ENTITY)."""
    return api_error(422, message)


def something_went_wrong() -> web.Response:
    """Catch-all 500 (handlers.rs:331-341)."""
    return api_error(500, "Something went wrong")


def parse_json(raw: bytes) -> object:
    return json.loads(raw)
