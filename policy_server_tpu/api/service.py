"""Request evaluation service — policy-server semantics around the raw
verdict.

Reference parity: src/api/service.rs —
* ``evaluate()`` (service.rs:30-151): always-accept-namespace short-circuit
  (40-71), PolicyInitialization errors converted to in-band 500 rejections
  (78-94), mode/mutation constraints applied for Validate but NOT Audit
  origin (108-116), metrics recorded from the *vanilla* pre-constraint
  verdict (118-150).
* ``validation_response_with_constraints`` (service.rs:160-208): protect mode
  strips patches from not-allowed-to-mutate policies and rejects; monitor
  mode always accepts, drops patch and status, and logs the would-be verdict.

The single-request ``evaluate`` here is the synchronous path (batch of one).
The micro-batching runtime (runtime/batcher.py) reuses the same
``pre_evaluate`` / ``post_evaluate`` halves around its fused batched
dispatch, so semantics and metrics stay identical on both paths.
"""

from __future__ import annotations

import enum
import time
from typing import Any

from policy_server_tpu.evaluation.environment import EvaluationEnvironment
from policy_server_tpu.evaluation.errors import (
    EvaluationError,
    PolicyInitializationError,
)
from policy_server_tpu.evaluation.policy_id import PolicyID
from policy_server_tpu.models import AdmissionResponse, ValidateRequest
from policy_server_tpu.models.policy import PolicyMode
from policy_server_tpu.telemetry import metrics as metrics_mod
from policy_server_tpu.telemetry.tracing import logger


class RequestOrigin(str, enum.Enum):
    """service.rs RequestOrigin: Validate applies constraints, Audit reports
    the raw verdict (service.rs:108-116)."""

    VALIDATE = "validate"
    AUDIT = "audit"

    def __str__(self) -> str:  # metric label value
        return self.value


def _registry() -> metrics_mod.MetricsRegistry:
    return metrics_mod.default_registry()


def _evaluation_metric(
    env: EvaluationEnvironment,
    policy_id: str,
    request: ValidateRequest,
    origin: RequestOrigin,
    accepted: bool,
    mutated: bool,
    error_code: int | None,
) -> metrics_mod.PolicyEvaluation | metrics_mod.RawPolicyEvaluation:
    mode = env.get_policy_mode(policy_id).value
    if request.is_raw:
        return metrics_mod.RawPolicyEvaluation(
            policy_name=policy_id,
            policy_mode=mode,
            accepted=accepted,
            mutated=mutated,
            error_code=error_code,
        )
    adm = request.admission_request
    request_kind = adm.request_kind.kind if adm.request_kind else ""
    return metrics_mod.PolicyEvaluation(
        policy_name=policy_id,
        policy_mode=mode,
        resource_kind=request_kind,
        resource_namespace=adm.namespace,
        resource_request_operation=adm.operation or "",
        accepted=accepted,
        mutated=mutated,
        request_origin=str(origin),
        error_code=error_code,
    )


def pre_evaluate(
    env: EvaluationEnvironment,
    policy_id: str,
    request: ValidateRequest,
    origin: RequestOrigin,
    start_time: float,
) -> AdmissionResponse | None:
    """The pre-dispatch half: id parse + always-accept-namespace shortcut
    (service.rs:37-71). Returns a final response, or None to proceed to
    evaluation. Raises EvaluationError for invalid/unknown ids."""
    PolicyID.parse(policy_id)  # raises InvalidPolicyId (service.rs:37)
    if not request.is_raw:
        ns = request.admission_request.namespace
        if ns and env.should_always_accept_requests_made_inside_of_namespace(ns):
            m = _evaluation_metric(
                env, policy_id, request, origin,
                accepted=True, mutated=False, error_code=None,
            )
            reg = _registry()
            reg.record_policy_latency(
                (time.perf_counter() - start_time) * 1e3, m
            )
            reg.add_policy_evaluation(m)
            return AdmissionResponse(uid=request.uid(), allowed=True)
    return None


def handle_initialization_error(
    request: ValidateRequest, error: PolicyInitializationError
) -> AdmissionResponse:
    """PolicyInitialization → in-band 500 rejection + error-counter metric
    (service.rs:78-94)."""
    _registry().add_policy_initialization_error(
        metrics_mod.PolicyInitializationError(
            policy_name=error.policy_id,
            initialization_error=str(error),
        )
    )
    return AdmissionResponse.reject(request.uid(), str(error), 500)


def validation_response_with_constraints(
    policy_id: str,
    policy_mode: PolicyMode,
    allowed_to_mutate: bool,
    response: AdmissionResponse,
) -> AdmissionResponse:
    """service.rs:160-208, byte-for-byte message parity."""
    if policy_mode is PolicyMode.PROTECT:
        if response.patch is not None and not allowed_to_mutate:
            out = response.copy()
            out.allowed = False
            out.status = _mutation_denied_status(policy_id)
            # validating webhooks must not carry a patch (service.rs comment)
            out.patch = None
            out.patch_type = None
            return out
        return response
    # Monitor mode: always accept, drop patch and status, log the would-be
    # verdict (service.rs:186-207).
    logger.info(
        "policy evaluation (monitor mode)",
        extra={
            "span_fields": {
                "policy_id": policy_id,
                "allowed_to_mutate": allowed_to_mutate,
                "response": repr(response.to_dict()),
            }
        },
    )
    out = response.copy()
    out.allowed = True
    out.patch = None
    out.patch_type = None
    out.status = None
    return out


def _mutation_denied_status(policy_id: str) -> Any:
    from policy_server_tpu.models import ValidationStatus

    return ValidationStatus(
        message=(
            f"Request rejected by policy {policy_id}. The policy attempted to "
            "mutate the request, but it is currently configured to not allow "
            "mutations."
        ),
        code=None,
    )


def post_evaluate(
    env: EvaluationEnvironment,
    policy_id: str,
    request: ValidateRequest,
    origin: RequestOrigin,
    vanilla: AdmissionResponse,
    start_time: float,
    metrics_sink: list | None = None,
    now: float | None = None,
) -> AdmissionResponse:
    """The post-dispatch half: constraints + metrics (service.rs:96-150).
    Metrics record the vanilla verdict; constraints apply only to the
    Validate origin. ``metrics_sink`` (the batcher's phase 3) collects
    ``(latency_ms, metric)`` pairs for one batched
    ``record_evaluations_batch`` flush instead of per-item recording;
    ``now`` lets the batcher share ONE clock read across the whole
    batch's latency computations."""
    policy_mode = env.get_policy_mode(policy_id)
    allowed_to_mutate = env.get_policy_allowed_to_mutate(policy_id)

    accepted = vanilla.allowed
    mutated = vanilla.patch is not None
    error_code = vanilla.status.code if vanilla.status else None

    if origin is RequestOrigin.VALIDATE:
        response = validation_response_with_constraints(
            policy_id, policy_mode, allowed_to_mutate, vanilla
        )
    else:
        response = vanilla

    m = _evaluation_metric(
        env, policy_id, request, origin,
        accepted=accepted, mutated=mutated, error_code=error_code,
    )
    end = now if now is not None else time.perf_counter()
    latency_ms = (end - start_time) * 1e3
    if metrics_sink is not None:
        metrics_sink.append((latency_ms, m))
    else:
        reg = _registry()
        reg.record_policy_latency(latency_ms, m)
        reg.add_policy_evaluation(m)
    return response


def evaluate(
    env: EvaluationEnvironment,
    policy_id: str,
    request: ValidateRequest,
    origin: RequestOrigin,
) -> AdmissionResponse:
    """Synchronous single-request evaluation (service.rs:30-151). Raises
    EvaluationError for InvalidPolicyId / PolicyNotFound (the HTTP layer maps
    them to 404/500, handlers.rs:321-342)."""
    start = time.perf_counter()
    short = pre_evaluate(env, policy_id, request, origin, start)
    if short is not None:
        return short
    try:
        vanilla = env.validate(policy_id, request)
    except PolicyInitializationError as e:
        return handle_initialization_error(request, e)
    return post_evaluate(env, policy_id, request, origin, vanilla, start)
