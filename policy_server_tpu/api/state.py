"""Shared API server state (reference src/api/state.rs:6-9 —
``ApiServerState{semaphore, evaluation_environment}``; here the semaphore's
role is played by the micro-batcher's bounded queue).

Round 9: the environment/batcher fields are the EPOCH POINTER of the
policy-lifecycle manager (lifecycle.py) — a hot reload promotes a new
epoch by rebinding them; handlers read them per request, so a request
racing the flip lands on one serving epoch or the other, never on a
torn pair that matters (the demoted epoch keeps draining)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from policy_server_tpu.evaluation.environment import EvaluationEnvironment
from policy_server_tpu.runtime.batcher import MicroBatcher


def readiness_verdict(
    ready: bool, batcher: Any, environment: Any
) -> tuple[int, str]:
    """One tenant's honest readiness verdict (status code, body text):
    503 until its first epoch is compiled+warmed, 200 on last-good
    during a background reload (the epoch flip never un-readies), and
    503 when every device shard's breaker is open under
    ``--degraded-mode reject`` — a tenant that would answer every
    review with an in-band 503 must not advertise ready. Shared by the
    process-wide probe and the per-tenant probes (tenancy.py)."""
    if not ready:
        return 503, "first policy epoch not yet compiled and warmed"
    if (
        batcher is not None
        and getattr(batcher, "degraded_mode", None) == "reject"
        and getattr(environment, "breaker_all_open", False)
    ):
        return (
            503,
            "every device shard breaker is open and --degraded-mode "
            "reject refuses traffic",
        )
    return 200, "ok"


@dataclass
class ApiServerState:
    evaluation_environment: EvaluationEnvironment
    batcher: MicroBatcher
    hostname: str = ""
    enable_pprof: bool = False
    # readiness honesty: False until the first policy epoch is compiled
    # AND warmed (lifecycle.install_first_epoch flips it). Defaults True
    # so directly-constructed states (tests, embedding) stay ready.
    ready: bool = True
    # the policy-lifecycle manager (lifecycle.PolicyLifecycleManager);
    # None when --policy-reload-mode off or when embedding without one
    lifecycle: Any = None
    # bearer token gating the /policies/* admin endpoints; None disables
    admin_token: str | None = None
    # the background audit scanner (audit.AuditScanner); None when
    # --audit-mode off — the GET /audit/reports endpoints then 404
    audit: Any = None
    # the live-cluster watch feed (audit.WatchFeed); None unless
    # --audit-watch — /metrics reads it through the state
    audit_watch: Any = None
    # the persistent verdict matrix (audit.VerdictMatrix); None unless
    # --audit-matrix — GET /audit/stream then 404s and /metrics exports
    # the matrix families as zero
    audit_matrix: Any = None
    # concurrent GET /audit/stream clients beyond which new subscribers
    # get an in-band 503 (--audit-stream-max-clients)
    audit_stream_max_clients: int = 64
    # live soak-window SLO observer (tools/soak engine, in-process
    # soaks): a dict of {rps, p99_ms, shed_rate} the engine refreshes
    # per window so /metrics exposes the soak's live trend; None outside
    # a soak (the gauge families export as zero)
    soak: Any = None
    # the native HTTP front-end (runtime/native_frontend.NativeFrontend);
    # None under --frontend python or after native-load fallback — the
    # /metrics framing counters read it through the state so the scrape
    # follows whatever is actually serving
    native_frontend: Any = None
    # native TLS termination manager (runtime/native_frontend.
    # NativeTlsManager); None under plaintext, --native-tls off, or the
    # loud aiohttp-TLS fallback — /metrics reads rotation generations
    # and handshake counters through it
    native_tls: Any = None
    # the last-good TLS identity machinery (certs.ReloadableTlsContext);
    # set whenever TLS is configured (native OR aiohttp termination) so
    # cert-expiry/reload observability does not depend on which frontend
    # terminates the handshake
    tls_reloadable: Any = None
    # the tenant registry (tenancy.TenantManager); None on single-tenant
    # deployments (no --tenants manifest) — every existing URL then maps
    # to this state's own epoch pointer, unchanged
    tenants: Any = None
    # the durable last-good state store (statestore.StateStore); None
    # without --state-dir — /metrics reads its counters through here
    statestore: Any = None
    # the boot report dict (warm/cold, time-to-ready, cache accounting);
    # populated by new_from_config, also persisted into the state dir
    boot_report: Any = None
    # supervision counters (supervision.SupervisorStats): worker
    # respawn/backoff/give-up + self-heal revives; None when embedding
    # without the server bootstrap
    supervisor: Any = None

    def _supervisor_note(self, body: str) -> str:
        """Append the honest-degradation note to a 200 readiness body:
        a pod serving with abandoned frontend worker slots is UP but
        degraded, and the probe's body must say so."""
        if self.supervisor is None:
            return body
        given_up = self.supervisor.stats().get("worker_slots_given_up", 0)
        if given_up:
            return (
                f"{body} (degraded: {given_up} frontend worker slot(s) "
                "gave up respawning after crash-looping)"
            )
        return body

    def readiness(self) -> tuple[int, str]:
        """The process-wide /readiness verdict. Single-tenant: this
        state's own honest verdict (readiness_verdict). Multi-tenant
        (round 16): 503 only when EVERY tenant is degraded — a partial
        outage keeps the pod in rotation (the healthy tenants' traffic
        must keep landing here), with the degraded tenant names in the
        200 body; per-tenant probes live at /readiness/{tenant}."""
        if self.tenants is None:
            code, body = readiness_verdict(
                self.ready, self.batcher, self.evaluation_environment
            )
            return code, (
                self._supervisor_note(body) if code == 200 else body
            )
        # the registry holds EVERY tenant incl. the default (whose
        # per-tenant verdict comes from the same readiness_verdict over
        # this state's raw fields — never this aggregate, no recursion)
        degraded = self.tenants.degraded_names()
        if not self.tenants.any_ready():
            return (
                503,
                "every tenant is degraded: " + ", ".join(degraded),
            )
        if degraded:
            return 200, self._supervisor_note(
                "ok (degraded tenants: " + ", ".join(degraded) + ")"
            )
        return 200, self._supervisor_note("ok")
