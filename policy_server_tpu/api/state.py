"""Shared API server state (reference src/api/state.rs:6-9 —
``ApiServerState{semaphore, evaluation_environment}``; here the semaphore's
role is played by the micro-batcher's bounded queue)."""

from __future__ import annotations

from dataclasses import dataclass

from policy_server_tpu.evaluation.environment import EvaluationEnvironment
from policy_server_tpu.runtime.batcher import MicroBatcher


@dataclass
class ApiServerState:
    evaluation_environment: EvaluationEnvironment
    batcher: MicroBatcher
    hostname: str = ""
    enable_pprof: bool = False
