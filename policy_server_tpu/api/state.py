"""Shared API server state (reference src/api/state.rs:6-9 —
``ApiServerState{semaphore, evaluation_environment}``; here the semaphore's
role is played by the micro-batcher's bounded queue).

Round 9: the environment/batcher fields are the EPOCH POINTER of the
policy-lifecycle manager (lifecycle.py) — a hot reload promotes a new
epoch by rebinding them; handlers read them per request, so a request
racing the flip lands on one serving epoch or the other, never on a
torn pair that matters (the demoted epoch keeps draining)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from policy_server_tpu.evaluation.environment import EvaluationEnvironment
from policy_server_tpu.runtime.batcher import MicroBatcher


@dataclass
class ApiServerState:
    evaluation_environment: EvaluationEnvironment
    batcher: MicroBatcher
    hostname: str = ""
    enable_pprof: bool = False
    # readiness honesty: False until the first policy epoch is compiled
    # AND warmed (lifecycle.install_first_epoch flips it). Defaults True
    # so directly-constructed states (tests, embedding) stay ready.
    ready: bool = True
    # the policy-lifecycle manager (lifecycle.PolicyLifecycleManager);
    # None when --policy-reload-mode off or when embedding without one
    lifecycle: Any = None
    # bearer token gating the /policies/* admin endpoints; None disables
    admin_token: str | None = None
    # the background audit scanner (audit.AuditScanner); None when
    # --audit-mode off — the GET /audit/reports endpoints then 404
    audit: Any = None
    # the live-cluster watch feed (audit.WatchFeed); None unless
    # --audit-watch — /metrics reads it through the state
    audit_watch: Any = None
    # live soak-window SLO observer (tools/soak engine, in-process
    # soaks): a dict of {rps, p99_ms, shed_rate} the engine refreshes
    # per window so /metrics exposes the soak's live trend; None outside
    # a soak (the gauge families export as zero)
    soak: Any = None
    # the native HTTP front-end (runtime/native_frontend.NativeFrontend);
    # None under --frontend python or after native-load fallback — the
    # /metrics framing counters read it through the state so the scrape
    # follows whatever is actually serving
    native_frontend: Any = None

    def readiness(self) -> tuple[int, str]:
        """The /readiness verdict (status code, body text). Honest on
        three axes: 503 until the first epoch is compiled+warmed, 200 on
        last-good while a background reload runs (the flip above never
        un-readies), and 503 when EVERY device shard's breaker is open
        under ``--degraded-mode reject`` — a server that would answer
        every review with an in-band 503 must not advertise ready."""
        if not self.ready:
            return 503, "first policy epoch not yet compiled and warmed"
        batcher = self.batcher
        if (
            batcher is not None
            and getattr(batcher, "degraded_mode", None) == "reject"
            and getattr(
                self.evaluation_environment, "breaker_all_open", False
            )
        ):
            return (
                503,
                "every device shard breaker is open and --degraded-mode "
                "reject refuses traffic",
            )
        return 200, "ok"
