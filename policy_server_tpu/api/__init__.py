"""HTTP API layer (reference src/api/): handlers, service semantics, state,
errors, profiling endpoints. See SURVEY.md §2.1 rows api::*."""

from policy_server_tpu.api.service import RequestOrigin, evaluate

__all__ = ["RequestOrigin", "evaluate"]
