"""On-demand profiling endpoints' engine.

Reference parity: src/profiling.rs —
* ``start_one_cpu_profile`` (profiling.rs:54-98): single-flight, default
  99 Hz / 30 s (profiling.rs:44-51), google-pprof protobuf output. Here the
  CPU profile is a host-side cProfile capture (pstats text), plus an
  optional JAX device trace: TPU "CPU time" lives in XLA, so the device
  trace (jax.profiler, viewable in TensorBoard/Perfetto) is the TPU-native
  equivalent of the sampling profiler.
* heap profile (profiling.rs:160-174, jemalloc_pprof): here
  ``tracemalloc`` host snapshot + per-device HBM stats from
  ``jax.Device.memory_stats()`` — the memory that actually matters on TPU.
"""

from __future__ import annotations

import collections
import json
import sys
import threading
import time
import tracemalloc
from dataclasses import dataclass

import jax

DEFAULT_PROFILING_FREQUENCY = 99  # Hz (profiling.rs:44-47)
DEFAULT_PROFILING_INTERVAL = 30  # seconds (profiling.rs:48-51)

# single-flight: only one profile at a time (profiling.rs:13-21, 61-63)
_cpu_lock = threading.Lock()


class ProfileInProgress(Exception):
    pass


@dataclass
class CpuProfile:
    text: str
    interval: float


def start_one_cpu_profile(
    interval: float, frequency: int = DEFAULT_PROFILING_FREQUENCY
) -> CpuProfile:
    """Process-wide sampling profile (the pprof-crate analog): every
    1/frequency seconds, snapshot ALL thread stacks via
    ``sys._current_frames`` and aggregate collapsed stacks. Output is
    flamegraph-collapsed text (``frame;frame;frame count`` lines), sorted by
    count. Single-flight: concurrent calls fail fast like the reference's
    mutex try_lock (profiling.rs:61-63)."""
    if not _cpu_lock.acquire(blocking=False):
        raise ProfileInProgress("a CPU profile is already being generated")
    try:
        period = 1.0 / max(1, frequency)
        stacks: collections.Counter[str] = collections.Counter()
        own = threading.get_ident()
        deadline = time.perf_counter() + interval
        while time.perf_counter() < deadline:
            for tid, frame in sys._current_frames().items():
                if tid == own:
                    continue
                parts = []
                f = frame
                while f is not None and len(parts) < 64:
                    code = f.f_code
                    parts.append(f"{code.co_filename}:{code.co_name}")
                    f = f.f_back
                stacks[";".join(reversed(parts))] += 1
            time.sleep(period)
        lines = [
            f"{stack} {count}"
            for stack, count in sorted(
                stacks.items(), key=lambda kv: -kv[1]
            )
        ]
        return CpuProfile(text="\n".join(lines) + "\n", interval=interval)
    finally:
        _cpu_lock.release()


_memory_profiling_active = False


def activate_memory_profiling() -> None:
    """Lazily start host allocation tracking at boot when --enable-pprof
    (profiling.rs:160-174)."""
    global _memory_profiling_active
    if not _memory_profiling_active:
        tracemalloc.start()
        _memory_profiling_active = True


def heap_profile() -> bytes:
    """Host top allocations + per-device HBM stats as JSON."""
    doc: dict = {"devices": [], "host_top_allocations": []}
    for dev in jax.devices():
        stats = {}
        try:
            stats = dev.memory_stats() or {}
        except Exception:  # pragma: no cover - backend-dependent
            pass
        doc["devices"].append(
            {"id": dev.id, "platform": dev.platform, "memory_stats": stats}
        )
    if _memory_profiling_active:
        snapshot = tracemalloc.take_snapshot()
        for stat in snapshot.statistics("lineno")[:50]:
            doc["host_top_allocations"].append(
                {
                    "location": str(stat.traceback),
                    "size_bytes": stat.size,
                    "count": stat.count,
                }
            )
    return json.dumps(doc, indent=2).encode()


def start_device_trace(log_dir: str) -> None:
    """Begin a JAX/XLA device trace (TensorBoard/Perfetto format)."""
    jax.profiler.start_trace(log_dir)


def stop_device_trace() -> None:
    jax.profiler.stop_trace()
