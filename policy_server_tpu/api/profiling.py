"""On-demand profiling endpoints' engine.

Reference parity: src/profiling.rs —
* ``start_one_cpu_profile`` (profiling.rs:54-98): single-flight, default
  99 Hz / 30 s (profiling.rs:44-51), google-pprof protobuf output. Here the
  CPU profile is a host-side cProfile capture (pstats text), plus an
  optional JAX device trace: TPU "CPU time" lives in XLA, so the device
  trace (jax.profiler, viewable in TensorBoard/Perfetto) is the TPU-native
  equivalent of the sampling profiler.
* heap profile (profiling.rs:160-174, jemalloc_pprof): here
  ``tracemalloc`` host snapshot + per-device HBM stats from
  ``jax.Device.memory_stats()`` — the memory that actually matters on TPU.
"""

from __future__ import annotations

import cProfile
import io
import json
import pstats
import threading
import time
import tracemalloc
from dataclasses import dataclass

import jax

DEFAULT_PROFILING_FREQUENCY = 99  # Hz (profiling.rs:44-47)
DEFAULT_PROFILING_INTERVAL = 30  # seconds (profiling.rs:48-51)

# single-flight: only one profile at a time (profiling.rs:13-21, 61-63)
_cpu_lock = threading.Lock()


class ProfileInProgress(Exception):
    pass


@dataclass
class CpuProfile:
    text: str
    interval: float


def start_one_cpu_profile(interval: float) -> CpuProfile:
    """Profile the host process for ``interval`` seconds. Single-flight:
    concurrent calls fail fast like the reference's mutex try_lock."""
    if not _cpu_lock.acquire(blocking=False):
        raise ProfileInProgress("a CPU profile is already being generated")
    try:
        profiler = cProfile.Profile()
        profiler.enable()
        time.sleep(interval)
        profiler.disable()
        buf = io.StringIO()
        stats = pstats.Stats(profiler, stream=buf)
        stats.sort_stats("cumulative").print_stats(100)
        return CpuProfile(text=buf.getvalue(), interval=interval)
    finally:
        _cpu_lock.release()


_memory_profiling_active = False


def activate_memory_profiling() -> None:
    """Lazily start host allocation tracking at boot when --enable-pprof
    (profiling.rs:160-174)."""
    global _memory_profiling_active
    if not _memory_profiling_active:
        tracemalloc.start()
        _memory_profiling_active = True


def heap_profile() -> bytes:
    """Host top allocations + per-device HBM stats as JSON."""
    doc: dict = {"devices": [], "host_top_allocations": []}
    for dev in jax.devices():
        stats = {}
        try:
            stats = dev.memory_stats() or {}
        except Exception:  # pragma: no cover - backend-dependent
            pass
        doc["devices"].append(
            {"id": dev.id, "platform": dev.platform, "memory_stats": stats}
        )
    if _memory_profiling_active:
        snapshot = tracemalloc.take_snapshot()
        for stat in snapshot.statistics("lineno")[:50]:
            doc["host_top_allocations"].append(
                {
                    "location": str(stat.traceback),
                    "size_bytes": stat.size,
                    "count": stat.count,
                }
            )
    return json.dumps(doc, indent=2).encode()


def start_device_trace(log_dir: str) -> None:
    """Begin a JAX/XLA device trace (TensorBoard/Perfetto format)."""
    jax.profiler.start_trace(log_dir)


def stop_device_trace() -> None:
    jax.profiler.stop_trace()
