"""WebAssembly interpreter — the execution half of the wasm substrate.

A classic stack machine over the pre-decoded flat instruction lists from
wasm/binary.py. Design choices:

* **Correctness over speed**: this backs the differential oracle and the
  host-side wasm policy path, not the TPU hot loop. Semantics follow the
  core spec: 32/64-bit wraparound, trap on OOB access / div-by-zero /
  bad indirect call, NaN-correct float ops where Python's floats agree.
* **Fuel limit** as the epoch-interruption analog (src/lib.rs:176-190):
  every executed instruction costs 1 fuel; exhaustion raises
  :class:`WasmFuelExhausted` and the caller maps it to the reference's
  "execution deadline exceeded" semantics.
* **Wall-clock deadline**: fuel bounds instructions, not time — a slow-
  but-terminating guest can exceed the policy timeout in real time
  without exhausting fuel. :func:`deadline_scope` arms an ambient
  (thread-local) absolute deadline; the dispatch loop checks the clock
  every 65536 instructions (piggybacked on the fuel countdown, ~ms
  granularity) and raises :class:`WasmDeadlineExceeded`, which IS a
  WasmFuelExhausted so callers map both to the reference's wall-clock
  epoch semantics (src/lib.rs:176-190).
* **Host imports** are plain Python callables registered per module+name;
  imported memories come from the embedder (the OPA ABI imports
  ``env.memory``).
"""

from __future__ import annotations

import contextlib
import math
import struct
import threading
import time
from typing import Any, Callable, Iterator, Mapping

from policy_server_tpu.wasm.binary import (
    ELSE,
    END,
    F32,
    F64,
    I32,
    I64,
    FuncType,
    Limits,
    WasmModule,
)

PAGE_SIZE = 65536

_U32 = 0xFFFFFFFF
_U64 = 0xFFFFFFFFFFFFFFFF


class WasmTrap(Exception):
    pass


class WasmFuelExhausted(WasmTrap):
    pass


class WasmDeadlineExceeded(WasmFuelExhausted):
    """Wall-clock budget exceeded (subclasses WasmFuelExhausted so every
    caller's deadline mapping covers both)."""


_ambient = threading.local()


@contextlib.contextmanager
def deadline_scope(seconds: float | None) -> Iterator[None]:
    """Arm a wall-clock budget for Instances created on this thread within
    the scope (nested scopes keep the TIGHTER deadline). ``None`` is a
    no-op — deadline disabled, reference parity with --policy-timeout 0."""
    if seconds is None:
        yield
        return
    prev = getattr(_ambient, "deadline", None)
    mine = time.monotonic() + seconds
    _ambient.deadline = mine if prev is None else min(prev, mine)
    try:
        yield
    finally:
        _ambient.deadline = prev


def _i32(v: int) -> int:
    v &= _U32
    return v - (1 << 32) if v & 0x80000000 else v


def _i64(v: int) -> int:
    v &= _U64
    return v - (1 << 64) if v & 0x8000000000000000 else v


def _u32(v: int) -> int:
    return v & _U32


def _u64(v: int) -> int:
    return v & _U64


def _f32(v: float) -> float:
    return struct.unpack("<f", struct.pack("<f", v))[0]


class Memory:
    """Linear memory with page-granular growth."""

    __slots__ = ("data", "maximum")

    def __init__(self, limits: Limits):
        self.data = bytearray(limits.minimum * PAGE_SIZE)
        self.maximum = limits.maximum

    @property
    def pages(self) -> int:
        return len(self.data) // PAGE_SIZE

    def grow(self, delta: int) -> int:
        old = self.pages
        new = old + delta
        if self.maximum is not None and new > self.maximum:
            return -1
        if new > 65536:
            return -1
        self.data.extend(b"\x00" * (delta * PAGE_SIZE))
        return old

    def read(self, addr: int, n: int) -> bytes:
        if addr < 0 or addr + n > len(self.data):
            raise WasmTrap("out of bounds memory access")
        return bytes(self.data[addr : addr + n])

    def write(self, addr: int, payload: bytes) -> None:
        if addr < 0 or addr + len(payload) > len(self.data):
            raise WasmTrap("out of bounds memory access")
        self.data[addr : addr + len(payload)] = payload


class HostFunc:
    __slots__ = ("fn", "functype")

    def __init__(self, fn: Callable, functype: FuncType):
        self.fn = fn
        self.functype = functype


class _Func:
    """A resolved module function (imported or local)."""

    __slots__ = ("functype", "host", "body", "locals")

    def __init__(self, functype, host=None, body=None, locals_=None):
        self.functype = functype
        self.host = host
        self.body = body
        self.locals = locals_ or []


class Instance:
    """One instantiated module: memories/tables/globals bound, start run."""

    def __init__(
        self,
        module: WasmModule,
        imports: Mapping[str, Mapping[str, Any]] | None = None,
        fuel: int | None = 500_000_000,
    ):
        self.module = module
        # ambient wall-clock deadline (deadline_scope) captured at
        # instantiation; the check piggybacks on the fuel countdown, so a
        # deadline with fuel disabled arms an effectively-infinite tank
        self.deadline = getattr(_ambient, "deadline", None)
        if self.deadline is not None and fuel is None:
            fuel = 1 << 62
        self.fuel = fuel
        imports = imports or {}
        self.funcs: list[_Func] = []
        self.memories: list[Memory] = []
        self.tables: list[list[int | None]] = []
        self.globals: list[list] = []  # [valtype, value] mutable cells
        self.dropped_data: set[int] = set()

        for imp in module.imports:
            provided = (imports.get(imp.module) or {}).get(imp.name)
            if provided is None:
                raise WasmTrap(
                    f"missing import {imp.module}.{imp.name} ({imp.kind})"
                )
            if imp.kind == "func":
                ft = module.types[imp.desc]
                if isinstance(provided, HostFunc):
                    self.funcs.append(_Func(provided.functype, host=provided.fn))
                else:
                    self.funcs.append(_Func(ft, host=provided))
            elif imp.kind == "memory":
                if not isinstance(provided, Memory):
                    raise WasmTrap("memory import must be a Memory")
                self.memories.append(provided)
            elif imp.kind == "table":
                self.tables.append(provided)
            elif imp.kind == "global":
                self.globals.append(provided)

        for i, typeidx in enumerate(module.functions):
            body = module.code[i]
            self.funcs.append(
                _Func(module.types[typeidx], body=body.code, locals_=body.locals)
            )
        for limits in module.memories:
            self.memories.append(Memory(limits))
        for limits in module.tables:
            self.tables.append([None] * limits.minimum)
        for g in module.globals:
            self.globals.append([g.valtype, self._const_eval(g.init)])

        for seg in module.elems:
            offset = self._const_eval(seg.offset)
            table = self.tables[seg.table]
            if offset + len(seg.func_indices) > len(table):
                raise WasmTrap("element segment out of bounds")
            for j, fidx in enumerate(seg.func_indices):
                table[offset + j] = fidx
        for idx, seg in enumerate(module.data):
            if seg.offset is None:
                continue  # passive
            offset = self._const_eval(seg.offset)
            self.memories[seg.memory].write(offset, seg.data)

        self._exports = module.export_map()
        if module.start is not None:
            self._call_index(module.start, [])

    # -- public API ---------------------------------------------------------

    @property
    def memory(self) -> Memory:
        return self.memories[0]

    def invoke(self, name: str, *args: int | float) -> list:
        exp = self._exports.get(name)
        if exp is None or exp.kind != "func":
            raise WasmTrap(f"no exported function {name!r}")
        return self._call_index(exp.index, list(args))

    def global_value(self, name: str):
        exp = self._exports.get(name)
        if exp is None or exp.kind != "global":
            raise WasmTrap(f"no exported global {name!r}")
        return self.globals[exp.index][1]

    # -- internals ----------------------------------------------------------

    def _const_eval(self, expr: list):
        stack: list = []
        for op, imm in expr:
            if op in (0x41, 0x42, 0x43, 0x44):
                stack.append(imm)
            elif op == 0x23:  # global.get
                stack.append(self.globals[imm][1])
            else:
                raise WasmTrap(f"unsupported const instr 0x{op:02x}")
        return stack[-1] if stack else 0

    def _call_index(self, index: int, args: list) -> list:
        fn = self.funcs[index]
        if fn.host is not None:
            result = fn.host(self, *args)
            if result is None:
                return []
            if isinstance(result, tuple):
                return list(result)
            return [result]
        return self._exec(fn, args)

    def _block_arity(self, bt) -> tuple[int, int]:
        """(param_count, result_count) of a blocktype."""
        if bt is None:
            return 0, 0
        if isinstance(bt, int) and bt in (I32, I64, F32, F64):
            return 0, 1
        ft = self.module.types[bt]
        return len(ft.params), len(ft.results)

    def _exec(self, fn: _Func, args: list) -> list:  # noqa: C901 —
        # the dispatch loop is one deliberate monolith: a function call per
        # opcode would dominate runtime
        module = self.module
        mem = self.memories[0] if self.memories else None
        locals_: list = list(args) + [
            0.0 if t in (F32, F64) else 0 for t in fn.locals
        ]
        stack: list = []
        # control stack entries: (label_pc, stack_height, arity, is_loop)
        ctrl: list = []
        code = fn.body
        pc = 0
        fuel = self.fuel
        deadline = self.deadline

        while True:
            if fuel is not None:
                fuel -= 1
                if fuel <= 0:
                    self.fuel = 0
                    raise WasmFuelExhausted("wasm fuel exhausted")
                if (
                    deadline is not None
                    and (fuel & 0xFFFF) == 0
                    and time.monotonic() >= deadline
                ):
                    self.fuel = fuel
                    raise WasmDeadlineExceeded(
                        "wasm wall-clock deadline exceeded"
                    )
            op, imm = code[pc]

            if op == 0x20:  # local.get
                stack.append(locals_[imm])
            elif op == 0x21:  # local.set
                locals_[imm] = stack.pop()
            elif op == 0x22:  # local.tee
                locals_[imm] = stack[-1]
            elif op == 0x41 or op == 0x42 or op == 0x43 or op == 0x44:
                stack.append(imm)
            elif op == 0x28:  # i32.load
                stack.append(
                    _i32(
                        int.from_bytes(
                            mem.read(_u32(stack.pop()) + imm, 4), "little"
                        )
                    )
                )
            elif op == 0x36:  # i32.store
                v = stack.pop()
                mem.write(_u32(stack.pop()) + imm, _u32(v).to_bytes(4, "little"))
            elif op == 0x02:  # block
                bt, end = imm
                params, results = self._block_arity(bt)
                ctrl.append((end, len(stack) - params, results, False))
            elif op == 0x03:  # loop
                bt, end = imm
                params, _results = self._block_arity(bt)
                ctrl.append((pc, len(stack) - params, params, True))
            elif op == 0x04:  # if
                bt, end, else_idx = imm
                cond = stack.pop()
                params, results = self._block_arity(bt)
                if cond:
                    ctrl.append((end, len(stack) - params, results, False))
                elif else_idx is not None:
                    ctrl.append((end, len(stack) - params, results, False))
                    pc = else_idx  # +1 below → first else-body instruction
                else:
                    pc = end  # +1 below → past END; no frame was pushed
            elif op == ELSE:
                # reached from the then-branch: jump to block end
                pc = imm
                ctrl.pop()
            elif op == END:
                if ctrl:
                    ctrl.pop()
                else:
                    ft = fn.functype
                    n = len(ft.results)
                    self.fuel = fuel  # writeback: consumed fuel must not
                    return stack[-n:] if n else []  # refund to the caller
            elif op == 0x0C:  # br
                npc = self._branch(imm, ctrl, stack)
                if npc is None:  # br targeting the function body = return
                    n = len(fn.functype.results)
                    self.fuel = fuel
                    return stack[-n:] if n else []
                pc = npc
                continue
            elif op == 0x0D:  # br_if
                if stack.pop():
                    npc = self._branch(imm, ctrl, stack)
                    if npc is None:
                        n = len(fn.functype.results)
                        self.fuel = fuel
                        return stack[-n:] if n else []
                    pc = npc
                    continue
            elif op == 0x0E:  # br_table
                targets, default = imm
                i = _u32(stack.pop())
                label = targets[i] if i < len(targets) else default
                npc = self._branch(label, ctrl, stack)
                if npc is None:
                    n = len(fn.functype.results)
                    self.fuel = fuel
                    return stack[-n:] if n else []
                pc = npc
                continue
            elif op == 0x0F:  # return
                ft = fn.functype
                n = len(ft.results)
                self.fuel = fuel
                return stack[-n:] if n else []
            elif op == 0x10:  # call
                callee = self.funcs[imm]
                n = len(callee.functype.params)
                call_args = stack[len(stack) - n :] if n else []
                del stack[len(stack) - n :]
                self.fuel = fuel
                stack.extend(self._call_index(imm, call_args))
                fuel = self.fuel
            elif op == 0x11:  # call_indirect
                typeidx, table_idx = imm
                elem = _u32(stack.pop())
                table = self.tables[table_idx]
                if elem >= len(table) or table[elem] is None:
                    raise WasmTrap("undefined element")
                findex = table[elem]
                callee = self.funcs[findex]
                if callee.functype != module.types[typeidx]:
                    raise WasmTrap("indirect call type mismatch")
                n = len(callee.functype.params)
                call_args = stack[len(stack) - n :] if n else []
                del stack[len(stack) - n :]
                self.fuel = fuel
                stack.extend(self._call_index(findex, call_args))
                fuel = self.fuel
            elif op == 0x00:
                raise WasmTrap("unreachable")
            elif op == 0x01:
                pass  # nop
            elif op == 0x1A:  # drop
                stack.pop()
            elif op == 0x1B:  # select
                c = stack.pop()
                b = stack.pop()
                a = stack.pop()
                stack.append(a if c else b)
            elif op == 0x23:  # global.get
                stack.append(self.globals[imm][1])
            elif op == 0x24:  # global.set
                self.globals[imm][1] = stack.pop()
            # ---- loads -----------------------------------------------------
            elif op == 0x29:  # i64.load
                stack.append(
                    _i64(int.from_bytes(mem.read(_u32(stack.pop()) + imm, 8), "little"))
                )
            elif op == 0x2A:  # f32.load
                stack.append(struct.unpack("<f", mem.read(_u32(stack.pop()) + imm, 4))[0])
            elif op == 0x2B:  # f64.load
                stack.append(struct.unpack("<d", mem.read(_u32(stack.pop()) + imm, 8))[0])
            elif op == 0x2C:  # i32.load8_s
                stack.append(
                    int.from_bytes(mem.read(_u32(stack.pop()) + imm, 1), "little", signed=True)
                )
            elif op == 0x2D:  # i32.load8_u
                stack.append(mem.read(_u32(stack.pop()) + imm, 1)[0])
            elif op == 0x2E:  # i32.load16_s
                stack.append(
                    int.from_bytes(mem.read(_u32(stack.pop()) + imm, 2), "little", signed=True)
                )
            elif op == 0x2F:  # i32.load16_u
                stack.append(int.from_bytes(mem.read(_u32(stack.pop()) + imm, 2), "little"))
            elif op == 0x30:  # i64.load8_s
                stack.append(
                    int.from_bytes(mem.read(_u32(stack.pop()) + imm, 1), "little", signed=True)
                )
            elif op == 0x31:
                stack.append(mem.read(_u32(stack.pop()) + imm, 1)[0])
            elif op == 0x32:
                stack.append(
                    int.from_bytes(mem.read(_u32(stack.pop()) + imm, 2), "little", signed=True)
                )
            elif op == 0x33:
                stack.append(int.from_bytes(mem.read(_u32(stack.pop()) + imm, 2), "little"))
            elif op == 0x34:  # i64.load32_s
                stack.append(
                    int.from_bytes(mem.read(_u32(stack.pop()) + imm, 4), "little", signed=True)
                )
            elif op == 0x35:  # i64.load32_u
                stack.append(int.from_bytes(mem.read(_u32(stack.pop()) + imm, 4), "little"))
            # ---- stores ----------------------------------------------------
            elif op == 0x37:  # i64.store
                v = stack.pop()
                mem.write(_u32(stack.pop()) + imm, _u64(v).to_bytes(8, "little"))
            elif op == 0x38:  # f32.store
                v = stack.pop()
                mem.write(_u32(stack.pop()) + imm, struct.pack("<f", v))
            elif op == 0x39:  # f64.store
                v = stack.pop()
                mem.write(_u32(stack.pop()) + imm, struct.pack("<d", v))
            elif op == 0x3A:  # i32.store8
                v = stack.pop()
                mem.write(_u32(stack.pop()) + imm, bytes([_u32(v) & 0xFF]))
            elif op == 0x3B:  # i32.store16
                v = stack.pop()
                mem.write(_u32(stack.pop()) + imm, (_u32(v) & 0xFFFF).to_bytes(2, "little"))
            elif op == 0x3C:  # i64.store8
                v = stack.pop()
                mem.write(_u32(stack.pop()) + imm, bytes([_u64(v) & 0xFF]))
            elif op == 0x3D:  # i64.store16
                v = stack.pop()
                mem.write(_u32(stack.pop()) + imm, (_u64(v) & 0xFFFF).to_bytes(2, "little"))
            elif op == 0x3E:  # i64.store32
                v = stack.pop()
                mem.write(_u32(stack.pop()) + imm, (_u64(v) & _U32).to_bytes(4, "little"))
            elif op == 0x3F:  # memory.size
                stack.append(mem.pages)
            elif op == 0x40:  # memory.grow
                stack.append(mem.grow(_u32(stack.pop())))
            # ---- i32 compare/arith ----------------------------------------
            elif op == 0x45:  # i32.eqz
                stack.append(1 if stack.pop() == 0 else 0)
            elif op == 0x46:
                stack.append(1 if _u32(stack.pop()) == _u32(stack.pop()) else 0)
            elif op == 0x47:
                stack.append(1 if _u32(stack.pop()) != _u32(stack.pop()) else 0)
            elif op == 0x48:  # i32.lt_s
                b, a = _i32(stack.pop()), _i32(stack.pop())
                stack.append(1 if a < b else 0)
            elif op == 0x49:  # i32.lt_u
                b, a = _u32(stack.pop()), _u32(stack.pop())
                stack.append(1 if a < b else 0)
            elif op == 0x4A:  # i32.gt_s
                b, a = _i32(stack.pop()), _i32(stack.pop())
                stack.append(1 if a > b else 0)
            elif op == 0x4B:  # i32.gt_u
                b, a = _u32(stack.pop()), _u32(stack.pop())
                stack.append(1 if a > b else 0)
            elif op == 0x4C:  # i32.le_s
                b, a = _i32(stack.pop()), _i32(stack.pop())
                stack.append(1 if a <= b else 0)
            elif op == 0x4D:  # i32.le_u
                b, a = _u32(stack.pop()), _u32(stack.pop())
                stack.append(1 if a <= b else 0)
            elif op == 0x4E:  # i32.ge_s
                b, a = _i32(stack.pop()), _i32(stack.pop())
                stack.append(1 if a >= b else 0)
            elif op == 0x4F:  # i32.ge_u
                b, a = _u32(stack.pop()), _u32(stack.pop())
                stack.append(1 if a >= b else 0)
            # ---- i64 compare ----------------------------------------------
            elif op == 0x50:
                stack.append(1 if stack.pop() == 0 else 0)
            elif op == 0x51:
                stack.append(1 if _u64(stack.pop()) == _u64(stack.pop()) else 0)
            elif op == 0x52:
                stack.append(1 if _u64(stack.pop()) != _u64(stack.pop()) else 0)
            elif op == 0x53:
                b, a = _i64(stack.pop()), _i64(stack.pop())
                stack.append(1 if a < b else 0)
            elif op == 0x54:
                b, a = _u64(stack.pop()), _u64(stack.pop())
                stack.append(1 if a < b else 0)
            elif op == 0x55:
                b, a = _i64(stack.pop()), _i64(stack.pop())
                stack.append(1 if a > b else 0)
            elif op == 0x56:
                b, a = _u64(stack.pop()), _u64(stack.pop())
                stack.append(1 if a > b else 0)
            elif op == 0x57:
                b, a = _i64(stack.pop()), _i64(stack.pop())
                stack.append(1 if a <= b else 0)
            elif op == 0x58:
                b, a = _u64(stack.pop()), _u64(stack.pop())
                stack.append(1 if a <= b else 0)
            elif op == 0x59:
                b, a = _i64(stack.pop()), _i64(stack.pop())
                stack.append(1 if a >= b else 0)
            elif op == 0x5A:
                b, a = _u64(stack.pop()), _u64(stack.pop())
                stack.append(1 if a >= b else 0)
            # ---- float compare --------------------------------------------
            elif op in (0x5B, 0x61):  # f32.eq / f64.eq
                b, a = stack.pop(), stack.pop()
                stack.append(1 if a == b else 0)
            elif op in (0x5C, 0x62):
                b, a = stack.pop(), stack.pop()
                stack.append(1 if a != b else 0)
            elif op in (0x5D, 0x63):
                b, a = stack.pop(), stack.pop()
                stack.append(1 if a < b else 0)
            elif op in (0x5E, 0x64):
                b, a = stack.pop(), stack.pop()
                stack.append(1 if a > b else 0)
            elif op in (0x5F, 0x65):
                b, a = stack.pop(), stack.pop()
                stack.append(1 if a <= b else 0)
            elif op in (0x60, 0x66):
                b, a = stack.pop(), stack.pop()
                stack.append(1 if a >= b else 0)
            # ---- i32 arithmetic -------------------------------------------
            elif op == 0x67:  # i32.clz
                v = _u32(stack.pop())
                stack.append(32 if v == 0 else 31 - v.bit_length() + 1)
            elif op == 0x68:  # i32.ctz
                v = _u32(stack.pop())
                stack.append(32 if v == 0 else (v & -v).bit_length() - 1)
            elif op == 0x69:  # i32.popcnt
                stack.append(bin(_u32(stack.pop())).count("1"))
            elif op == 0x6A:
                b, a = stack.pop(), stack.pop()
                stack.append(_i32(a + b))
            elif op == 0x6B:
                b, a = stack.pop(), stack.pop()
                stack.append(_i32(a - b))
            elif op == 0x6C:
                b, a = stack.pop(), stack.pop()
                stack.append(_i32(a * b))
            elif op == 0x6D:  # i32.div_s
                b, a = _i32(stack.pop()), _i32(stack.pop())
                if b == 0:
                    raise WasmTrap("integer divide by zero")
                q = abs(a) // abs(b)
                q = -q if (a < 0) != (b < 0) else q
                if q > 0x7FFFFFFF:
                    raise WasmTrap("integer overflow")
                stack.append(_i32(q))
            elif op == 0x6E:  # i32.div_u
                b, a = _u32(stack.pop()), _u32(stack.pop())
                if b == 0:
                    raise WasmTrap("integer divide by zero")
                stack.append(_i32(a // b))
            elif op == 0x6F:  # i32.rem_s
                b, a = _i32(stack.pop()), _i32(stack.pop())
                if b == 0:
                    raise WasmTrap("integer divide by zero")
                r = abs(a) % abs(b)
                stack.append(_i32(-r if a < 0 else r))
            elif op == 0x70:  # i32.rem_u
                b, a = _u32(stack.pop()), _u32(stack.pop())
                if b == 0:
                    raise WasmTrap("integer divide by zero")
                stack.append(_i32(a % b))
            elif op == 0x71:
                b, a = stack.pop(), stack.pop()
                stack.append(_i32(_u32(a) & _u32(b)))
            elif op == 0x72:
                b, a = stack.pop(), stack.pop()
                stack.append(_i32(_u32(a) | _u32(b)))
            elif op == 0x73:
                b, a = stack.pop(), stack.pop()
                stack.append(_i32(_u32(a) ^ _u32(b)))
            elif op == 0x74:  # i32.shl
                b, a = stack.pop(), stack.pop()
                stack.append(_i32(_u32(a) << (b & 31)))
            elif op == 0x75:  # i32.shr_s
                b, a = stack.pop(), _i32(stack.pop())
                stack.append(_i32(a >> (b & 31)))
            elif op == 0x76:  # i32.shr_u
                b, a = stack.pop(), _u32(stack.pop())
                stack.append(_i32(a >> (b & 31)))
            elif op == 0x77:  # i32.rotl
                b, a = stack.pop() & 31, _u32(stack.pop())
                stack.append(_i32(((a << b) | (a >> (32 - b))) & _U32))
            elif op == 0x78:  # i32.rotr
                b, a = stack.pop() & 31, _u32(stack.pop())
                stack.append(_i32(((a >> b) | (a << (32 - b))) & _U32))
            # ---- i64 arithmetic -------------------------------------------
            elif op == 0x79:
                v = _u64(stack.pop())
                stack.append(64 if v == 0 else 64 - v.bit_length())
            elif op == 0x7A:
                v = _u64(stack.pop())
                stack.append(64 if v == 0 else (v & -v).bit_length() - 1)
            elif op == 0x7B:
                stack.append(bin(_u64(stack.pop())).count("1"))
            elif op == 0x7C:
                b, a = stack.pop(), stack.pop()
                stack.append(_i64(a + b))
            elif op == 0x7D:
                b, a = stack.pop(), stack.pop()
                stack.append(_i64(a - b))
            elif op == 0x7E:
                b, a = stack.pop(), stack.pop()
                stack.append(_i64(a * b))
            elif op == 0x7F:
                b, a = _i64(stack.pop()), _i64(stack.pop())
                if b == 0:
                    raise WasmTrap("integer divide by zero")
                q = abs(a) // abs(b)
                q = -q if (a < 0) != (b < 0) else q
                if q > 0x7FFFFFFFFFFFFFFF:
                    raise WasmTrap("integer overflow")
                stack.append(_i64(q))
            elif op == 0x80:
                b, a = _u64(stack.pop()), _u64(stack.pop())
                if b == 0:
                    raise WasmTrap("integer divide by zero")
                stack.append(_i64(a // b))
            elif op == 0x81:
                b, a = _i64(stack.pop()), _i64(stack.pop())
                if b == 0:
                    raise WasmTrap("integer divide by zero")
                r = abs(a) % abs(b)
                stack.append(_i64(-r if a < 0 else r))
            elif op == 0x82:
                b, a = _u64(stack.pop()), _u64(stack.pop())
                if b == 0:
                    raise WasmTrap("integer divide by zero")
                stack.append(_i64(a % b))
            elif op == 0x83:
                b, a = stack.pop(), stack.pop()
                stack.append(_i64(_u64(a) & _u64(b)))
            elif op == 0x84:
                b, a = stack.pop(), stack.pop()
                stack.append(_i64(_u64(a) | _u64(b)))
            elif op == 0x85:
                b, a = stack.pop(), stack.pop()
                stack.append(_i64(_u64(a) ^ _u64(b)))
            elif op == 0x86:
                b, a = stack.pop(), stack.pop()
                stack.append(_i64(_u64(a) << (b & 63)))
            elif op == 0x87:
                b, a = stack.pop(), _i64(stack.pop())
                stack.append(_i64(a >> (b & 63)))
            elif op == 0x88:
                b, a = stack.pop(), _u64(stack.pop())
                stack.append(_i64(a >> (b & 63)))
            elif op == 0x89:
                b, a = stack.pop() & 63, _u64(stack.pop())
                stack.append(_i64(((a << b) | (a >> (64 - b))) & _U64))
            elif op == 0x8A:
                b, a = stack.pop() & 63, _u64(stack.pop())
                stack.append(_i64(((a >> b) | (a << (64 - b))) & _U64))
            # ---- float arithmetic -----------------------------------------
            elif op in (0x8B, 0x99):  # abs
                stack.append(abs(stack.pop()))
            elif op in (0x8C, 0x9A):  # neg
                stack.append(-stack.pop())
            elif op in (0x8D, 0x9B):  # ceil
                stack.append(float(math.ceil(stack.pop())))
            elif op in (0x8E, 0x9C):  # floor
                stack.append(float(math.floor(stack.pop())))
            elif op in (0x8F, 0x9D):  # trunc
                stack.append(float(math.trunc(stack.pop())))
            elif op in (0x90, 0x9E):  # nearest
                v = stack.pop()
                f = math.floor(v)
                d = v - f
                if d > 0.5:
                    n = f + 1
                elif d < 0.5:
                    n = f
                else:
                    n = f if f % 2 == 0 else f + 1
                stack.append(float(n))
            elif op in (0x91, 0x9F):  # sqrt
                stack.append(math.sqrt(stack.pop()))
            elif op == 0x92:
                b, a = stack.pop(), stack.pop()
                stack.append(_f32(a + b))
            elif op == 0x93:
                b, a = stack.pop(), stack.pop()
                stack.append(_f32(a - b))
            elif op == 0x94:
                b, a = stack.pop(), stack.pop()
                stack.append(_f32(a * b))
            elif op == 0x95:
                b, a = stack.pop(), stack.pop()
                stack.append(_f32(a / b) if b != 0 else math.copysign(math.inf, a) * math.copysign(1, b) if a != 0 else math.nan)
            elif op == 0x96:  # f32.min
                b, a = stack.pop(), stack.pop()
                stack.append(min(a, b))
            elif op == 0x97:
                b, a = stack.pop(), stack.pop()
                stack.append(max(a, b))
            elif op == 0x98:  # f32.copysign
                b, a = stack.pop(), stack.pop()
                stack.append(math.copysign(a, b))
            elif op == 0xA0:
                b, a = stack.pop(), stack.pop()
                stack.append(a + b)
            elif op == 0xA1:
                b, a = stack.pop(), stack.pop()
                stack.append(a - b)
            elif op == 0xA2:
                b, a = stack.pop(), stack.pop()
                stack.append(a * b)
            elif op == 0xA3:  # f64.div
                b, a = stack.pop(), stack.pop()
                if b == 0:
                    stack.append(
                        math.nan if a == 0 else math.copysign(math.inf, a) * math.copysign(1.0, b)
                    )
                else:
                    stack.append(a / b)
            elif op == 0xA4:
                b, a = stack.pop(), stack.pop()
                stack.append(min(a, b))
            elif op == 0xA5:
                b, a = stack.pop(), stack.pop()
                stack.append(max(a, b))
            elif op == 0xA6:
                b, a = stack.pop(), stack.pop()
                stack.append(math.copysign(a, b))
            # ---- conversions ----------------------------------------------
            elif op == 0xA7:  # i32.wrap_i64
                stack.append(_i32(stack.pop()))
            elif op in (0xA8, 0xAA):  # i32.trunc_f32_s / f64_s
                v = stack.pop()
                if math.isnan(v) or math.isinf(v):
                    raise WasmTrap("invalid conversion to integer")
                t = math.trunc(v)
                if not -(2**31) <= t <= 2**31 - 1:
                    raise WasmTrap("integer overflow")
                stack.append(int(t))
            elif op in (0xA9, 0xAB):  # i32.trunc_f32_u / f64_u
                v = stack.pop()
                if math.isnan(v) or math.isinf(v):
                    raise WasmTrap("invalid conversion to integer")
                t = math.trunc(v)
                if not 0 <= t <= 2**32 - 1:
                    raise WasmTrap("integer overflow")
                stack.append(_i32(int(t)))
            elif op == 0xAC:  # i64.extend_i32_s
                stack.append(_i32(stack.pop()))
            elif op == 0xAD:  # i64.extend_i32_u
                stack.append(_u32(stack.pop()))
            elif op in (0xAE, 0xB0):  # i64.trunc_f32_s / f64_s
                v = stack.pop()
                if math.isnan(v) or math.isinf(v):
                    raise WasmTrap("invalid conversion to integer")
                t = math.trunc(v)
                if not -(2**63) <= t <= 2**63 - 1:
                    raise WasmTrap("integer overflow")
                stack.append(int(t))
            elif op in (0xAF, 0xB1):  # i64.trunc_f32_u / f64_u
                v = stack.pop()
                if math.isnan(v) or math.isinf(v):
                    raise WasmTrap("invalid conversion to integer")
                t = math.trunc(v)
                if not 0 <= t <= 2**64 - 1:
                    raise WasmTrap("integer overflow")
                stack.append(_i64(int(t)))
            elif op in (0xB2, 0xB3):  # f32.convert_i32_s/u
                v = stack.pop()
                stack.append(_f32(float(v if op == 0xB2 else _u32(v))))
            elif op in (0xB4, 0xB5):  # f32.convert_i64_s/u
                v = stack.pop()
                stack.append(_f32(float(v if op == 0xB4 else _u64(v))))
            elif op == 0xB6:  # f32.demote_f64
                stack.append(_f32(stack.pop()))
            elif op in (0xB7, 0xB8):  # f64.convert_i32_s/u
                v = stack.pop()
                stack.append(float(v if op == 0xB7 else _u32(v)))
            elif op in (0xB9, 0xBA):  # f64.convert_i64_s/u
                v = stack.pop()
                stack.append(float(v if op == 0xB9 else _u64(v)))
            elif op == 0xBB:  # f64.promote_f32
                stack.append(float(stack.pop()))
            elif op == 0xBC:  # i32.reinterpret_f32
                stack.append(_i32(struct.unpack("<I", struct.pack("<f", stack.pop()))[0]))
            elif op == 0xBD:  # i64.reinterpret_f64
                stack.append(_i64(struct.unpack("<Q", struct.pack("<d", stack.pop()))[0]))
            elif op == 0xBE:  # f32.reinterpret_i32
                stack.append(struct.unpack("<f", struct.pack("<I", _u32(stack.pop())))[0])
            elif op == 0xBF:  # f64.reinterpret_i64
                stack.append(struct.unpack("<d", struct.pack("<Q", _u64(stack.pop())))[0])
            # ---- sign extension -------------------------------------------
            elif op == 0xC0:  # i32.extend8_s
                v = stack.pop() & 0xFF
                stack.append(v - 256 if v & 0x80 else v)
            elif op == 0xC1:  # i32.extend16_s
                v = stack.pop() & 0xFFFF
                stack.append(v - 65536 if v & 0x8000 else v)
            elif op == 0xC2:  # i64.extend8_s
                v = stack.pop() & 0xFF
                stack.append(v - 256 if v & 0x80 else v)
            elif op == 0xC3:
                v = stack.pop() & 0xFFFF
                stack.append(v - 65536 if v & 0x8000 else v)
            elif op == 0xC4:  # i64.extend32_s
                stack.append(_i32(stack.pop()))
            # ---- 0xFC extensions ------------------------------------------
            elif op >= 0xFC00:
                sub = op & 0xFF
                if sub in (0, 1, 2, 3, 4, 5, 6, 7):  # saturating trunc
                    v = stack.pop()
                    signed = sub % 2 == 0
                    to64 = sub >= 4
                    if math.isnan(v):
                        stack.append(0)
                    else:
                        t = math.trunc(v) if not math.isinf(v) else (
                            math.inf if v > 0 else -math.inf
                        )
                        bits = 64 if to64 else 32
                        if signed:
                            lo, hi = -(2 ** (bits - 1)), 2 ** (bits - 1) - 1
                        else:
                            lo, hi = 0, 2**bits - 1
                        t = max(lo, min(hi, t))
                        stack.append(
                            (_i64 if to64 else _i32)(int(t) & ((1 << bits) - 1))
                        )
                elif sub == 8:  # memory.init
                    n = _u32(stack.pop())
                    src = _u32(stack.pop())
                    dst = _u32(stack.pop())
                    seg = module.data[imm]
                    if imm in self.dropped_data:
                        if n:
                            raise WasmTrap("data segment dropped")
                    payload = seg.data[src : src + n]
                    if len(payload) != n:
                        raise WasmTrap("out of bounds memory.init")
                    mem.write(dst, payload)
                elif sub == 9:  # data.drop
                    self.dropped_data.add(imm)
                elif sub == 10:  # memory.copy
                    n = _u32(stack.pop())
                    src = _u32(stack.pop())
                    dst = _u32(stack.pop())
                    chunk = mem.read(src, n)
                    mem.write(dst, chunk)
                elif sub == 11:  # memory.fill
                    n = _u32(stack.pop())
                    val = stack.pop() & 0xFF
                    dst = _u32(stack.pop())
                    # bounds-trap BEFORE building the fill buffer: n can be
                    # ~4 GiB and hostile wasm must not force that allocation
                    if dst + n > len(mem.data):
                        raise WasmTrap("out of bounds memory access")
                    mem.data[dst : dst + n] = bytes([val]) * n
                else:
                    raise WasmTrap(f"unsupported extended op {sub}")
            else:
                raise WasmTrap(f"unsupported opcode 0x{op:02x}")
            pc += 1

    @staticmethod
    def _branch(label: int, ctrl: list, stack: list) -> int | None:
        """Apply a br to the ``label``-th enclosing block; returns the new
        pc, or None when the branch targets the implicit function-body
        label (= return)."""
        if label >= len(ctrl):
            return None
        for _ in range(label):
            ctrl.pop()
        target_pc, height, arity, is_loop = ctrl[-1]
        results = stack[len(stack) - arity :] if arity else []
        del stack[height:]
        stack.extend(results)
        if is_loop:
            return target_pc + 1  # continue after the loop header
        ctrl.pop()
        return target_pc + 1  # continue after the matching end
