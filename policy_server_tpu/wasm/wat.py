"""Minimal WAT (WebAssembly text) assembler.

Covers the subset this repo's wasm-oracle policies are written in — flat
(non-folded) instruction syntax, named functions/locals/labels, one
memory, data segments, exports and func imports. The output of
:func:`assemble` feeds wasm/binary.py's decoder, so every authored policy
round-trips through the same binary format a real toolchain would emit.

Grammar (s-expressions):

    (module
      (import "env" "host_fn" (func $host (param i32) (result i32)))
      (memory 1) | (memory (export "memory") 1)
      (data (i32.const 8) "bytes\\00")
      (global $g (mut i32) (i32.const 0))
      (func $name (export "name") (param $x i32) (result i32) (local $t i32)
        local.get $x
        i32.const 1
        i32.add)
      (export "name" (func $name)))

Control flow: ``block $label [result]`` / ``loop $label`` /
``if [result]`` / ``else`` / ``end``; branches take label names or
depths."""

from __future__ import annotations

import struct
from typing import Any

from policy_server_tpu.wasm.binary import F32, F64, I32, I64


class WatError(Exception):
    pass


# -- s-expression parsing ----------------------------------------------------


def _tokenize(src: str) -> list[str]:
    out: list[str] = []
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        if c in "()":
            out.append(c)
            i += 1
        elif c == '"':
            j = i + 1
            buf = []
            while src[j] != '"':
                if src[j] == "\\":
                    esc = src[j + 1]
                    if esc == "n":
                        buf.append("\n")
                        j += 2
                    elif esc == "t":
                        buf.append("\t")
                        j += 2
                    elif esc in ('"', "\\"):
                        buf.append(esc)
                        j += 2
                    else:  # \xx hex byte
                        buf.append(chr(int(src[j + 1 : j + 3], 16)))
                        j += 3
                else:
                    buf.append(src[j])
                    j += 1
            out.append('"' + "".join(buf))
            i = j + 1
        elif c == ";" and i + 1 < n and src[i + 1] == ";":
            while i < n and src[i] != "\n":
                i += 1
        elif c == "(" or c.isspace():
            i += 1
        else:
            j = i
            while j < n and not src[j].isspace() and src[j] not in '()"':
                j += 1
            out.append(src[i:j])
            i = j
    return out


def _parse(tokens: list[str]):
    pos = 0

    def node():
        nonlocal pos
        tok = tokens[pos]
        if tok == "(":
            pos += 1
            items = []
            while tokens[pos] != ")":
                items.append(node())
            pos += 1
            return items
        pos += 1
        return tok

    result = node()
    if pos != len(tokens):
        raise WatError("trailing tokens")
    return result


# -- encoding helpers --------------------------------------------------------


def _uleb(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _sleb(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if (v == 0 and not b & 0x40) or (v == -1 and b & 0x40):
            out.append(b)
            return bytes(out)
        out.append(b | 0x80)


def _vec(items: list[bytes]) -> bytes:
    return _uleb(len(items)) + b"".join(items)


def _name(s: str) -> bytes:
    raw = s.encode()
    return _uleb(len(raw)) + raw


_VALTYPES = {"i32": I32, "i64": I64, "f32": F32, "f64": F64}

# opcode table for plain (no-immediate) instructions
_SIMPLE = {
    "unreachable": 0x00, "nop": 0x01, "return": 0x0F, "drop": 0x1A,
    "select": 0x1B, "memory.size": None, "memory.grow": None,
    "i32.eqz": 0x45, "i32.eq": 0x46, "i32.ne": 0x47, "i32.lt_s": 0x48,
    "i32.lt_u": 0x49, "i32.gt_s": 0x4A, "i32.gt_u": 0x4B, "i32.le_s": 0x4C,
    "i32.le_u": 0x4D, "i32.ge_s": 0x4E, "i32.ge_u": 0x4F,
    "i64.eqz": 0x50, "i64.eq": 0x51, "i64.ne": 0x52, "i64.lt_s": 0x53,
    "i64.lt_u": 0x54, "i64.gt_s": 0x55, "i64.gt_u": 0x56, "i64.le_s": 0x57,
    "i64.le_u": 0x58, "i64.ge_s": 0x59, "i64.ge_u": 0x5A,
    "f64.eq": 0x61, "f64.ne": 0x62, "f64.lt": 0x63, "f64.gt": 0x64,
    "f64.le": 0x65, "f64.ge": 0x66,
    "i32.clz": 0x67, "i32.ctz": 0x68, "i32.popcnt": 0x69,
    "i32.add": 0x6A, "i32.sub": 0x6B, "i32.mul": 0x6C, "i32.div_s": 0x6D,
    "i32.div_u": 0x6E, "i32.rem_s": 0x6F, "i32.rem_u": 0x70,
    "i32.and": 0x71, "i32.or": 0x72, "i32.xor": 0x73, "i32.shl": 0x74,
    "i32.shr_s": 0x75, "i32.shr_u": 0x76, "i32.rotl": 0x77, "i32.rotr": 0x78,
    "i64.add": 0x7C, "i64.sub": 0x7D, "i64.mul": 0x7E, "i64.div_s": 0x7F,
    "i64.div_u": 0x80, "i64.rem_s": 0x81, "i64.rem_u": 0x82,
    "i64.and": 0x83, "i64.or": 0x84, "i64.xor": 0x85, "i64.shl": 0x86,
    "i64.shr_s": 0x87, "i64.shr_u": 0x88,
    "f64.add": 0xA0, "f64.sub": 0xA1, "f64.mul": 0xA2, "f64.div": 0xA3,
    "i32.wrap_i64": 0xA7, "i64.extend_i32_s": 0xAC, "i64.extend_i32_u": 0xAD,
    "f64.convert_i32_s": 0xB7, "i32.trunc_f64_s": 0xAA,
}

_MEM_OPCODES = {
    "i32.load": 0x28, "i64.load": 0x29, "f32.load": 0x2A, "f64.load": 0x2B,
    "i32.load8_s": 0x2C, "i32.load8_u": 0x2D, "i32.load16_s": 0x2E,
    "i32.load16_u": 0x2F, "i64.load8_u": 0x31, "i64.load32_u": 0x35,
    "i32.store": 0x36, "i64.store": 0x37, "f32.store": 0x38,
    "f64.store": 0x39, "i32.store8": 0x3A, "i32.store16": 0x3B,
}


class _FuncAsm:
    def __init__(self, asm: "_ModuleAsm", params, results, locals_, names):
        self.asm = asm
        self.params = params
        self.results = results
        self.locals = locals_
        self.local_names = names  # name → index (params first)
        self.body = bytearray()
        self.labels: list[str | None] = []

    def _local_index(self, tok: str) -> int:
        if tok.startswith("$"):
            if tok not in self.local_names:
                raise WatError(f"unknown local {tok}")
            return self.local_names[tok]
        return int(tok)

    def _label_depth(self, tok: str) -> int:
        if tok.startswith("$"):
            for depth, name in enumerate(reversed(self.labels)):
                if name == tok:
                    return depth
            raise WatError(f"unknown label {tok}")
        return int(tok)

    def emit(self, instrs: list, i: int = 0) -> None:
        body = self.body
        n = len(instrs)
        while i < n:
            tok = instrs[i]
            if not isinstance(tok, str):
                raise WatError(f"folded expressions unsupported: {tok}")
            i += 1
            if tok in ("block", "loop", "if"):
                label = None
                if i < n and isinstance(instrs[i], str) and instrs[i].startswith("$"):
                    label = instrs[i]
                    i += 1
                bt = 0x40
                if (
                    i < n
                    and isinstance(instrs[i], list)
                    and instrs[i]
                    and instrs[i][0] == "result"
                ):
                    bt = _VALTYPES[instrs[i][1]]
                    i += 1
                body.append({"block": 0x02, "loop": 0x03, "if": 0x04}[tok])
                body.append(bt)
                self.labels.append(label)
            elif tok == "else":
                body.append(0x05)
            elif tok == "end":
                body.append(0x0B)
                if self.labels:
                    self.labels.pop()
            elif tok in ("br", "br_if"):
                body.append(0x0C if tok == "br" else 0x0D)
                body += _uleb(self._label_depth(instrs[i]))
                i += 1
            elif tok == "br_table":
                targets = []
                while i < n and isinstance(instrs[i], str) and (
                    instrs[i].startswith("$") or instrs[i].isdigit()
                ):
                    targets.append(self._label_depth(instrs[i]))
                    i += 1
                body.append(0x0E)
                body += _uleb(len(targets) - 1)
                for t in targets[:-1]:
                    body += _uleb(t)
                body += _uleb(targets[-1])
            elif tok == "call":
                body.append(0x10)
                body += _uleb(self.asm.func_index(instrs[i]))
                i += 1
            elif tok in ("local.get", "local.set", "local.tee"):
                body.append({"local.get": 0x20, "local.set": 0x21, "local.tee": 0x22}[tok])
                body += _uleb(self._local_index(instrs[i]))
                i += 1
            elif tok in ("global.get", "global.set"):
                body.append(0x23 if tok == "global.get" else 0x24)
                body += _uleb(self.asm.global_index(instrs[i]))
                i += 1
            elif tok == "i32.const":
                body.append(0x41)
                body += _sleb(int(instrs[i], 0))
                i += 1
            elif tok == "i64.const":
                body.append(0x42)
                body += _sleb(int(instrs[i], 0))
                i += 1
            elif tok == "f64.const":
                body.append(0x44)
                body += struct.pack("<d", float(instrs[i]))
                i += 1
            elif tok in _MEM_OPCODES:
                offset = 0
                if i < n and isinstance(instrs[i], str) and instrs[i].startswith("offset="):
                    offset = int(instrs[i].split("=", 1)[1], 0)
                    i += 1
                body.append(_MEM_OPCODES[tok])
                body += _uleb(0)  # align
                body += _uleb(offset)
            elif tok == "memory.size":
                body += b"\x3f\x00"
            elif tok == "memory.grow":
                body += b"\x40\x00"
            elif tok == "memory.copy":
                body += b"\xfc\x0a\x00\x00"
            elif tok == "memory.fill":
                body += b"\xfc\x0b\x00"
            elif tok in _SIMPLE and _SIMPLE[tok] is not None:
                body.append(_SIMPLE[tok])
            else:
                raise WatError(f"unsupported instruction {tok!r}")


class _ModuleAsm:
    def __init__(self):
        self.types: list[tuple[tuple, tuple]] = []
        self.imports: list[bytes] = []
        self.func_names: dict[str, int] = {}
        self.func_typeidx: list[int] = []  # local funcs
        self.n_imported = 0
        self.global_names: dict[str, int] = {}
        self.globals: list[bytes] = []
        self.exports: list[bytes] = []
        self.memory: tuple[int, int | None] | None = None
        self.datas: list[bytes] = []
        self.bodies: list[bytes] = []

    def typeidx(self, params: tuple, results: tuple) -> int:
        key = (params, results)
        if key not in self.types:
            self.types.append(key)
        return self.types.index(key)

    def func_index(self, tok: str) -> int:
        if tok.startswith("$"):
            if tok not in self.func_names:
                raise WatError(f"unknown function {tok}")
            return self.func_names[tok]
        return int(tok)

    def global_index(self, tok: str) -> int:
        if tok.startswith("$"):
            return self.global_names[tok]
        return int(tok)


def _sig_of(items: list) -> tuple[tuple, tuple, dict]:
    """Parse (param ...) / (result ...) clauses → (params, results, names)."""
    params: list[int] = []
    results: list[int] = []
    names: dict[str, int] = {}
    for clause in items:
        if isinstance(clause, list) and clause and clause[0] == "param":
            rest = clause[1:]
            if rest and rest[0].startswith("$"):
                names[rest[0]] = len(params)
                params.append(_VALTYPES[rest[1]])
            else:
                params.extend(_VALTYPES[t] for t in rest)
        elif isinstance(clause, list) and clause and clause[0] == "result":
            results.extend(_VALTYPES[t] for t in clause[1:])
    return tuple(params), tuple(results), names


def assemble(source: str) -> bytes:
    """WAT text → wasm binary."""
    tree = _parse(_tokenize(source))
    if not tree or tree[0] != "module":
        raise WatError("expected (module ...)")
    asm = _ModuleAsm()

    funcs: list[tuple[list, Any]] = []  # deferred bodies

    # pass 1: declare everything so call/$name resolves forward refs
    for form in tree[1:]:
        head = form[0]
        if head == "import":
            module, name = form[1][1:], form[2][1:]
            desc = form[3]
            if desc[0] != "func":
                raise WatError("only func imports supported in WAT subset")
            fname = None
            rest = desc[1:]
            if rest and isinstance(rest[0], str) and rest[0].startswith("$"):
                fname = rest[0]
                rest = rest[1:]
            params, results, _ = _sig_of(rest)
            ti = asm.typeidx(params, results)
            asm.imports.append(_name(module) + _name(name) + b"\x00" + _uleb(ti))
            if fname:
                asm.func_names[fname] = asm.n_imported
            asm.n_imported += 1
        elif head == "func":
            rest = form[1:]
            fname = None
            if rest and isinstance(rest[0], str) and rest[0].startswith("$"):
                fname = rest[0]
                rest = rest[1:]
            index = asm.n_imported + len(asm.func_typeidx)
            if fname:
                asm.func_names[fname] = index
            export_clauses = [
                c for c in rest if isinstance(c, list) and c and c[0] == "export"
            ]
            for e in export_clauses:
                asm.exports.append(_name(e[1][1:]) + b"\x00" + _uleb(index))
            sig_rest = [
                c for c in rest
                if isinstance(c, list) and c and c[0] in ("param", "result")
            ]
            params, results, names = _sig_of(sig_rest)
            asm.func_typeidx.append(asm.typeidx(params, results))
            funcs.append((rest, (params, results, names, index)))
        elif head == "memory":
            rest = form[1:]
            export = None
            if rest and isinstance(rest[0], list) and rest[0][0] == "export":
                export = rest[0][1][1:]
                rest = rest[1:]
            minimum = int(rest[0])
            maximum = int(rest[1]) if len(rest) > 1 else None
            asm.memory = (minimum, maximum)
            if export:
                asm.exports.append(_name(export) + b"\x02" + _uleb(0))
        elif head == "data":
            offset_expr = form[1]
            payload = form[2][1:].encode("latin-1")
            seg = (
                b"\x00"
                + b"\x41"
                + _sleb(int(offset_expr[1], 0))
                + b"\x0b"
                + _uleb(len(payload))
                + payload
            )
            asm.datas.append(seg)
        elif head == "global":
            rest = form[1:]
            gname = None
            if rest and isinstance(rest[0], str) and rest[0].startswith("$"):
                gname = rest[0]
                rest = rest[1:]
            gtype = rest[0]
            mutable = isinstance(gtype, list) and gtype[0] == "mut"
            vt = _VALTYPES[gtype[1] if mutable else gtype]
            init = rest[1]
            expr = b"\x41" + _sleb(int(init[1], 0)) + b"\x0b"
            if gname:
                asm.global_names[gname] = len(asm.globals)
            asm.globals.append(
                bytes([vt, 1 if mutable else 0]) + expr
            )
        elif head == "export":
            kind = form[2][0]
            target = form[2][1]
            kinds = {"func": 0, "table": 1, "memory": 2, "global": 3}
            if kind == "func":
                idx = asm.func_index(target)
            elif kind == "global":
                idx = asm.global_index(target)
            else:
                idx = int(str(target).lstrip("$") or 0)
            asm.exports.append(_name(form[1][1:]) + bytes([kinds[kind]]) + _uleb(idx))
        else:
            raise WatError(f"unsupported module form {head!r}")

    # pass 2: assemble bodies
    for rest, (params, results, names, _index) in funcs:
        locals_: list[int] = []
        for clause in rest:
            if isinstance(clause, list) and clause and clause[0] == "local":
                lrest = clause[1:]
                if lrest and lrest[0].startswith("$"):
                    names[lrest[0]] = len(params) + len(locals_)
                    locals_.append(_VALTYPES[lrest[1]])
                else:
                    locals_.extend(_VALTYPES[t] for t in lrest)
        instrs = [
            c for c in rest
            if not (
                isinstance(c, list)
                and c
                and c[0] in ("param", "result", "local", "export")
            )
        ]
        fb = _FuncAsm(asm, params, results, locals_, names)
        fb.emit(instrs)
        fb.body.append(0x0B)  # end
        # locals vector: run-length encode
        runs: list[tuple[int, int]] = []
        for vt in locals_:
            if runs and runs[-1][1] == vt:
                runs[-1] = (runs[-1][0] + 1, vt)
            else:
                runs.append((1, vt))
        locals_enc = _uleb(len(runs)) + b"".join(
            _uleb(c) + bytes([vt]) for c, vt in runs
        )
        body = locals_enc + bytes(fb.body)
        asm.bodies.append(_uleb(len(body)) + body)

    # emit sections
    def section(sid: int, payload: bytes) -> bytes:
        return bytes([sid]) + _uleb(len(payload)) + payload

    out = bytearray(b"\x00asm\x01\x00\x00\x00")
    type_entries = [
        b"\x60"
        + _uleb(len(p))
        + bytes(p)
        + _uleb(len(r))
        + bytes(r)
        for p, r in asm.types
    ]
    out += section(1, _vec(type_entries))
    if asm.imports:
        out += section(2, _vec(asm.imports))
    if asm.func_typeidx:
        out += section(3, _vec([_uleb(t) for t in asm.func_typeidx]))
    if asm.memory is not None:
        mn, mx = asm.memory
        lim = (b"\x01" + _uleb(mn) + _uleb(mx)) if mx is not None else (b"\x00" + _uleb(mn))
        out += section(5, _vec([lim]))
    if asm.globals:
        out += section(6, _vec(asm.globals))
    if asm.exports:
        out += section(7, _vec(asm.exports))
    if asm.bodies:
        out += section(10, _vec(asm.bodies))
    if asm.datas:
        out += section(11, _vec(asm.datas))
    return bytes(out)
