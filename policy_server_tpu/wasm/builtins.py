"""OPA builtins host registry — the burrego equivalent.

Rego policies compiled to wasm leave any builtin the compiler cannot inline
as a host call: the module's ``builtins()`` export declares a
``name → id`` map and the generated code invokes
``opa_builtin{0..4}(id, ctx, args...)`` expecting the host to supply the
implementation. The reference ships the burrego builtins set and banners
it in ``--long-version`` (/root/reference/src/cli.rs:7-21; SURVEY.md §2.2
burrego row). This module is that registry for the TPU build: pure-Python
implementations over decoded JSON values, dispatched by wasm/opa.py.

Implemented families (the common Gatekeeper/Kubewarden surface): strings
(incl. sprintf), regex, glob, sets, json/base64/urlquery encoding, semver,
units, and time.now_ns. Errors raise ``BuiltinError`` — evaluation fails
loudly like burrego's host-callback errors, never silently undefined.
"""

from __future__ import annotations

import base64 as _b64
import json
import re
import time
import urllib.parse
from typing import Any, Callable


class BuiltinError(Exception):
    """A builtin received invalid arguments or failed to compute."""


def _expect_str(v: Any, builtin: str, pos: int) -> str:
    if not isinstance(v, str):
        raise BuiltinError(f"{builtin}: operand {pos} must be string, got {type(v).__name__}")
    return v


def _expect_num(v: Any, builtin: str, pos: int):
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise BuiltinError(f"{builtin}: operand {pos} must be number, got {type(v).__name__}")
    return v


def _expect_arr(v: Any, builtin: str, pos: int) -> list:
    if not isinstance(v, list):
        raise BuiltinError(f"{builtin}: operand {pos} must be array, got {type(v).__name__}")
    return v


# ---------------------------------------------------------------------------
# sprintf — Go fmt verb subset (%v %s %d %f %x %o %b %e %g %t %% with
# width/precision/zero-pad flags), the verbs Gatekeeper templates use
# ---------------------------------------------------------------------------

_VERB_RE = re.compile(r"%([-+ 0#]*)(\d+)?(?:\.(\d+))?([vsdfxXoObeEgGtq%])")


def _go_repr(v: Any) -> str:
    """%v rendering, close to Go's fmt for JSON-shaped values."""
    if v is None:
        return "<nil>"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    if isinstance(v, (list, dict)):
        return json.dumps(v, separators=(", ", ": "))
    return str(v)


def sprintf(fmt: Any, args: Any) -> str:
    fmt = _expect_str(fmt, "sprintf", 1)
    values = list(_expect_arr(args, "sprintf", 2))
    out: list[str] = []
    pos = 0
    i = 0
    while i < len(fmt):
        ch = fmt[i]
        if ch != "%":
            out.append(ch)
            i += 1
            continue
        m = _VERB_RE.match(fmt, i)
        if not m:
            out.append(ch)
            i += 1
            continue
        flags, width, prec, verb = m.groups()
        i = m.end()
        if verb == "%":
            out.append("%")
            continue
        if pos >= len(values):
            out.append(f"%!{verb}(MISSING)")
            continue
        v = values[pos]
        pos += 1
        try:
            if verb == "t":
                s = "true" if v else "false"
            elif verb in "dxXoOb":
                n = int(_expect_num(v, "sprintf", pos))
                base = {"d": "d", "x": "x", "X": "X", "o": "o", "O": "o", "b": "b"}[verb]
                s = format(n, base)
                if verb == "O":
                    s = "0o" + s
            elif verb in "feEgG":
                n = float(_expect_num(v, "sprintf", pos))
                p = int(prec) if prec is not None else 6
                if verb == "f":
                    s = f"{n:.{p}f}"
                else:
                    s = format(n, f".{p}{verb}")
            elif verb == "q":
                s = json.dumps(str(v))
            elif verb == "s":
                s = v if isinstance(v, str) else _go_repr(v)
            else:  # %v
                s = _go_repr(v)
        except BuiltinError:
            s = f"%!{verb}({_go_repr(v)})"
        if prec is not None and verb == "s":
            s = s[: int(prec)]
        if width:
            w = int(width)
            if "-" in flags:
                s = s.ljust(w)
            elif "0" in flags and verb in "dxXoObfeEgG":
                neg = s.startswith("-")
                body = s[1:] if neg else s
                s = ("-" if neg else "") + body.rjust(w - (1 if neg else 0), "0")
            else:
                s = s.rjust(w)
        out.append(s)
    return "".join(out)


# ---------------------------------------------------------------------------
# strings
# ---------------------------------------------------------------------------


def _concat(delim: Any, coll: Any) -> str:
    delim = _expect_str(delim, "concat", 1)
    parts = [_expect_str(x, "concat", 2) for x in _expect_arr(coll, "concat", 2)]
    return delim.join(parts)


def _format_int(n: Any, base: Any) -> str:
    n = int(_expect_num(n, "format_int", 1))
    base = int(_expect_num(base, "format_int", 2))
    if base == 2:
        s = format(abs(n), "b")
    elif base == 8:
        s = format(abs(n), "o")
    elif base == 10:
        s = str(abs(n))
    elif base == 16:
        s = format(abs(n), "x")
    else:
        raise BuiltinError(f"format_int: unsupported base {base}")
    return ("-" if n < 0 else "") + s


def _substring(s: Any, start: Any, length: Any) -> str:
    s = _expect_str(s, "substring", 1)
    start = int(_expect_num(start, "substring", 2))
    length = int(_expect_num(length, "substring", 3))
    if start < 0:
        raise BuiltinError("substring: negative offset")
    return s[start:] if length < 0 else s[start : start + length]


def _trim_left(s: Any, cutset: Any) -> str:
    return _expect_str(s, "trim_left", 1).lstrip(_expect_str(cutset, "trim_left", 2))


def _trim_right(s: Any, cutset: Any) -> str:
    return _expect_str(s, "trim_right", 1).rstrip(_expect_str(cutset, "trim_right", 2))


def _trim_prefix(s: Any, prefix: Any) -> str:
    s = _expect_str(s, "trim_prefix", 1)
    prefix = _expect_str(prefix, "trim_prefix", 2)
    return s[len(prefix):] if s.startswith(prefix) else s


def _trim_suffix(s: Any, suffix: Any) -> str:
    s = _expect_str(s, "trim_suffix", 1)
    suffix = _expect_str(suffix, "trim_suffix", 2)
    return s[: len(s) - len(suffix)] if suffix and s.endswith(suffix) else s


# ---------------------------------------------------------------------------
# regex (RE2-flavored patterns; Python re is a superset — policies using
# RE2-only syntax behave identically, backreference patterns would be
# rejected by OPA's own compiler anyway)
# ---------------------------------------------------------------------------


def _compile_re(pattern: str, builtin: str) -> re.Pattern:
    try:
        return re.compile(pattern)
    except re.error as e:
        raise BuiltinError(f"{builtin}: invalid pattern {pattern!r}: {e}") from e


def _regex_match(pattern: Any, value: Any) -> bool:
    return bool(
        _compile_re(_expect_str(pattern, "regex.match", 1), "regex.match").search(
            _expect_str(value, "regex.match", 2)
        )
    )


def _regex_is_valid(pattern: Any) -> bool:
    if not isinstance(pattern, str):
        return False
    try:
        re.compile(pattern)
        return True
    except re.error:
        return False


def _regex_split(pattern: Any, value: Any) -> list[str]:
    return _compile_re(_expect_str(pattern, "regex.split", 1), "regex.split").split(
        _expect_str(value, "regex.split", 2)
    )


def _regex_find_n(pattern: Any, value: Any, n: Any) -> list[str]:
    n = int(_expect_num(n, "regex.find_n", 3))
    matches = _compile_re(
        _expect_str(pattern, "regex.find_n", 1), "regex.find_n"
    ).finditer(_expect_str(value, "regex.find_n", 2))
    # OPA returns the FULL match text regardless of capture groups
    flat = [m.group(0) for m in matches]
    return flat if n < 0 else flat[:n]


def _go_replacement_to_python(repl: str, compiled: re.Pattern) -> str:
    """Go/RE2 replacement syntax → Python re.sub replacement: ``$1`` /
    ``${name}`` are group references, ``$$`` is a literal ``$``, a lone
    ``$`` is literal text, and — Go Expand semantics — a reference to a
    group the pattern does not define expands to the EMPTY string rather
    than erroring."""

    def group_ref(name: str) -> str:
        if name.isdigit():
            return f"\\g<{name}>" if int(name) <= compiled.groups else ""
        return f"\\g<{name}>" if name in compiled.groupindex else ""

    out: list[str] = []
    i = 0
    n = len(repl)
    while i < n:
        c = repl[i]
        if c == "\\":
            out.append("\\\\")
            i += 1
            continue
        if c != "$":
            out.append(c)
            i += 1
            continue
        if i + 1 < n and repl[i + 1] == "$":
            out.append("$")
            i += 2
            continue
        if i + 1 < n and repl[i + 1] == "{":
            j = repl.find("}", i + 2)
            if j > 0:
                out.append(group_ref(repl[i + 2 : j]))
                i = j + 1
                continue
        j = i + 1
        while j < n and (repl[j].isalnum() or repl[j] == "_"):
            j += 1
        if j > i + 1:
            out.append(group_ref(repl[i + 1 : j]))
            i = j
            continue
        out.append("$")  # lone $: literal
        i += 1
    return "".join(out)


def _regex_replace(value: Any, pattern: Any, replacement: Any) -> str:
    compiled = _compile_re(
        _expect_str(pattern, "regex.replace", 2), "regex.replace"
    )
    try:
        return compiled.sub(
            _go_replacement_to_python(
                _expect_str(replacement, "regex.replace", 3), compiled
            ),
            _expect_str(value, "regex.replace", 1),
        )
    except re.error as e:
        raise BuiltinError(f"regex.replace: {e}") from e


# ---------------------------------------------------------------------------
# glob (gobwas/glob semantics subset: * ? ** [..] {a,b} with delimiters)
# ---------------------------------------------------------------------------


def _glob_to_regex(pattern: str, delimiters: list[str]) -> str:
    delim = "".join(re.escape(d) for d in delimiters)
    any_nodelim = f"[^{delim}]*" if delim else ".*"
    one_nodelim = f"[^{delim}]" if delim else "."
    out = []
    i = 0
    n = len(pattern)
    while i < n:
        c = pattern[i]
        if c == "*":
            if i + 1 < n and pattern[i + 1] == "*":
                out.append(".*")
                i += 2
            else:
                out.append(any_nodelim)
                i += 1
        elif c == "?":
            out.append(one_nodelim)
            i += 1
        elif c == "[":
            j = pattern.find("]", i + 1)
            if j < 0:
                raise BuiltinError(f"glob.match: unterminated class in {pattern!r}")
            cls = pattern[i + 1 : j]
            if cls.startswith("!"):
                cls = "^" + cls[1:]
            out.append("[" + cls + "]")
            i = j + 1
        elif c == "{":
            j = pattern.find("}", i + 1)
            if j < 0:
                raise BuiltinError(f"glob.match: unterminated alternate in {pattern!r}")
            alts = pattern[i + 1 : j].split(",")
            out.append(
                "(?:" + "|".join(_glob_to_regex(a, delimiters)[2:-2] for a in alts) + ")"
            )
            i = j + 1
        else:
            out.append(re.escape(c))
            i += 1
    return r"\A" + "".join(out) + r"\Z"


def _glob_match(pattern: Any, delimiters: Any, value: Any) -> bool:
    pattern = _expect_str(pattern, "glob.match", 1)
    if delimiters is None:
        delims = ["."]
    else:
        delims = [_expect_str(d, "glob.match", 2) for d in _expect_arr(delimiters, "glob.match", 2)]
    value = _expect_str(value, "glob.match", 3)
    try:
        return bool(re.match(_glob_to_regex(pattern, delims), value))
    except re.error as e:
        raise BuiltinError(f"glob.match: bad pattern {pattern!r}: {e}") from e


def _glob_quote_meta(pattern: Any) -> str:
    pattern = _expect_str(pattern, "glob.quote_meta", 1)
    return re.sub(r"([*?\[\]{}\\])", r"\\\1", pattern)


# ---------------------------------------------------------------------------
# sets (OPA sets cross the wasm boundary serialized as arrays)
# ---------------------------------------------------------------------------


def _freeze(v: Any):
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, list):
        return tuple(_freeze(x) for x in v)
    return v


def _dedup(items: list) -> list:
    seen = set()
    out = []
    for x in items:
        k = _freeze(x)
        if k not in seen:
            seen.add(k)
            out.append(x)
    return out


def _set_intersection(sets: Any) -> list:
    sets = [_expect_arr(s, "intersection", 1) for s in _expect_arr(sets, "intersection", 1)]
    if not sets:
        return []
    keys = set(_freeze(x) for x in sets[0])
    for s in sets[1:]:
        keys &= set(_freeze(x) for x in s)
    return _dedup([x for x in sets[0] if _freeze(x) in keys])


def _set_union(sets: Any) -> list:
    sets = [_expect_arr(s, "union", 1) for s in _expect_arr(sets, "union", 1)]
    out: list = []
    for s in sets:
        out.extend(s)
    return _dedup(out)


# ---------------------------------------------------------------------------
# encodings
# ---------------------------------------------------------------------------


def _json_unmarshal(s: Any) -> Any:
    try:
        return json.loads(_expect_str(s, "json.unmarshal", 1))
    except ValueError as e:
        raise BuiltinError(f"json.unmarshal: {e}") from e


def _json_is_valid(s: Any) -> bool:
    if not isinstance(s, str):
        return False
    try:
        json.loads(s)
        return True
    except ValueError:
        return False


def _b64_decode(s: Any) -> str:
    try:
        return _b64.b64decode(_expect_str(s, "base64.decode", 1), validate=True).decode()
    except Exception as e:
        raise BuiltinError(f"base64.decode: {e}") from e


def _b64url_decode(s: Any) -> str:
    s = _expect_str(s, "base64url.decode", 1)
    pad = "=" * (-len(s) % 4)
    try:
        return _b64.urlsafe_b64decode(s + pad).decode()
    except Exception as e:
        raise BuiltinError(f"base64url.decode: {e}") from e


# ---------------------------------------------------------------------------
# semver
# ---------------------------------------------------------------------------

_SEMVER_RE = re.compile(
    r"^(0|[1-9]\d*)\.(0|[1-9]\d*)\.(0|[1-9]\d*)"
    r"(?:-((?:0|[1-9]\d*|\d*[A-Za-z-][0-9A-Za-z-]*)"
    r"(?:\.(?:0|[1-9]\d*|\d*[A-Za-z-][0-9A-Za-z-]*))*))?"
    r"(?:\+([0-9A-Za-z-]+(?:\.[0-9A-Za-z-]+)*))?$"
)


def _semver_parse(s: str):
    m = _SEMVER_RE.match(s)
    if not m:
        raise BuiltinError(f"semver.compare: invalid semver {s!r}")
    major, minor, patch, pre, _build = m.groups()
    return (int(major), int(minor), int(patch)), pre


def _semver_compare(a: Any, b: Any) -> int:
    (va, pa) = _semver_parse(_expect_str(a, "semver.compare", 1))
    (vb, pb) = _semver_parse(_expect_str(b, "semver.compare", 2))
    if va != vb:
        return -1 if va < vb else 1
    if pa == pb:
        return 0
    if pa is None:
        return 1  # release > pre-release
    if pb is None:
        return -1

    def key(pre: str):
        parts = []
        for p in pre.split("."):
            parts.append((0, int(p), "") if p.isdigit() else (1, 0, p))
        return parts

    ka, kb = key(pa), key(pb)
    if ka == kb:
        return 0
    return -1 if ka < kb else 1


def _semver_is_valid(s: Any) -> bool:
    return isinstance(s, str) and bool(_SEMVER_RE.match(s))


# ---------------------------------------------------------------------------
# units (Kubernetes quantity suffixes — the Gatekeeper resource-limit case)
# ---------------------------------------------------------------------------

_BYTE_UNITS = {
    "": 1,
    "ki": 2**10, "mi": 2**20, "gi": 2**30, "ti": 2**40, "pi": 2**50, "ei": 2**60,
    "k": 10**3, "m": 10**6, "g": 10**9, "t": 10**12, "p": 10**15, "e": 10**18,
    "kb": 10**3, "mb": 10**6, "gb": 10**9, "tb": 10**12, "pb": 10**15, "eb": 10**18,
    "kib": 2**10, "mib": 2**20, "gib": 2**30, "tib": 2**40, "pib": 2**50, "eib": 2**60,
}

_UNITS_RE = re.compile(r'^\s*"?\s*([0-9]+(?:\.[0-9]+)?)\s*([A-Za-z]*)\s*"?\s*$')


def _units_parse_bytes(s: Any):
    s = _expect_str(s, "units.parse_bytes", 1)
    m = _UNITS_RE.match(s)
    if not m:
        raise BuiltinError(f"units.parse_bytes: cannot parse {s!r}")
    num, unit = m.groups()
    mult = _BYTE_UNITS.get(unit.lower())
    if mult is None:
        raise BuiltinError(f"units.parse_bytes: unknown unit {unit!r}")
    val = float(num) * mult
    return int(val) if val.is_integer() else val


# SI suffixes are CASE-SENSITIVE ('m' milli vs 'M' mega — the K8s
# cpu-vs-memory distinction); binary suffixes are case-insensitive.
_SI_UNITS = {
    "": 1, "m": 1e-3, "k": 10**3, "K": 10**3, "M": 10**6, "G": 10**9,
    "T": 10**12, "P": 10**15, "E": 10**18,
}
_BINARY_UNITS = {
    "ki": 2**10, "mi": 2**20, "gi": 2**30, "ti": 2**40, "pi": 2**50,
    "ei": 2**60,
}


def _units_parse(s: Any):
    """OPA units.parse: SI + binary suffixes, 'm' = milli (K8s CPU)."""
    s = _expect_str(s, "units.parse", 1)
    m = _UNITS_RE.match(s)
    if not m:
        raise BuiltinError(f"units.parse: cannot parse {s!r}")
    num, unit = m.groups()
    mult = _SI_UNITS.get(unit)
    if mult is None:
        mult = _BINARY_UNITS.get(unit.lower())
    if mult is None:
        raise BuiltinError(f"units.parse: unknown unit {unit!r}")
    val = float(num) * mult
    return int(val) if float(val).is_integer() else val


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------

REGISTRY: dict[str, Callable[..., Any]] = {
    # strings
    "concat": _concat,
    "contains": lambda s, sub: _expect_str(sub, "contains", 2) in _expect_str(s, "contains", 1),
    "endswith": lambda s, suf: _expect_str(s, "endswith", 1).endswith(_expect_str(suf, "endswith", 2)),
    "startswith": lambda s, pre: _expect_str(s, "startswith", 1).startswith(_expect_str(pre, "startswith", 2)),
    "format_int": _format_int,
    "indexof": lambda s, sub: _expect_str(s, "indexof", 1).find(_expect_str(sub, "indexof", 2)),
    "lower": lambda s: _expect_str(s, "lower", 1).lower(),
    "upper": lambda s: _expect_str(s, "upper", 1).upper(),
    "replace": lambda s, old, new: _expect_str(s, "replace", 1).replace(
        _expect_str(old, "replace", 2), _expect_str(new, "replace", 3)
    ),
    "split": lambda s, d: _expect_str(s, "split", 1).split(_expect_str(d, "split", 2)),
    "sprintf": sprintf,
    "substring": _substring,
    "trim": lambda s, cutset: _expect_str(s, "trim", 1).strip(_expect_str(cutset, "trim", 2)),
    "trim_left": _trim_left,
    "trim_prefix": _trim_prefix,
    "trim_right": _trim_right,
    "trim_suffix": _trim_suffix,
    "trim_space": lambda s: _expect_str(s, "trim_space", 1).strip(),
    # regex
    "regex.match": _regex_match,
    "re_match": _regex_match,  # deprecated OPA alias, still emitted
    "regex.is_valid": _regex_is_valid,
    "regex.split": _regex_split,
    "regex.find_n": _regex_find_n,
    "regex.replace": _regex_replace,
    # glob
    "glob.match": _glob_match,
    "glob.quote_meta": _glob_quote_meta,
    # sets
    "intersection": _set_intersection,
    "union": _set_union,
    # encodings
    "json.marshal": lambda v: json.dumps(v, separators=(",", ":")),
    "json.unmarshal": _json_unmarshal,
    "json.is_valid": _json_is_valid,
    "base64.encode": lambda s: _b64.b64encode(_expect_str(s, "base64.encode", 1).encode()).decode(),
    "base64.decode": _b64_decode,
    "base64.is_valid": lambda s: isinstance(s, str)
    and bool(re.fullmatch(r"[A-Za-z0-9+/]*={0,2}", s))
    and len(s) % 4 == 0,
    "base64url.encode": lambda s: _b64.urlsafe_b64encode(
        _expect_str(s, "base64url.encode", 1).encode()
    ).decode(),
    "base64url.encode_no_pad": lambda s: _b64.urlsafe_b64encode(
        _expect_str(s, "base64url.encode_no_pad", 1).encode()
    ).decode().rstrip("="),
    "base64url.decode": _b64url_decode,
    "urlquery.encode": lambda s: urllib.parse.quote_plus(_expect_str(s, "urlquery.encode", 1)),
    "urlquery.decode": lambda s: urllib.parse.unquote_plus(_expect_str(s, "urlquery.decode", 1)),
    # semver
    "semver.compare": _semver_compare,
    "semver.is_valid": _semver_is_valid,
    # units
    "units.parse_bytes": _units_parse_bytes,
    "units.parse": _units_parse,
    # time
    "time.now_ns": lambda: time.time_ns(),
}


def get_builtins() -> dict[str, Callable[..., Any]]:
    """Name → implementation map (the burrego::get_builtins() analog used
    by the --long-version banner, /root/reference/src/cli.rs:7-21)."""
    return dict(REGISTRY)
