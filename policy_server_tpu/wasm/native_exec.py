"""ctypes bridge to the native wasm execution core (csrc/wasmint.cpp).

The Python interpreter (wasm/interp.py) is the semantic reference; this
bridge translates a decoded module's flat instruction lists into the
op/immediate arrays the C++ dispatch loop executes, and exposes a
NativeInstance with the SAME surface as interp.Instance (invoke, memory
read/write, global_value, ambient deadline, fuel) so the waPC/OPA/WASI
hosts run unchanged on either engine. Anything the native core does not
model (imported memories/tables/globals, table.* extended ops) raises
NativeUnsupported and the caller falls back to the Python engine — and
``PSTPU_NO_NATIVE_WASM=1`` disables the native path entirely.

Build model mirrors ops/fastenc.py: compiled on demand with g++ into
``build/wasmint-<py>.so`` and cached; any build failure degrades to the
Python interpreter silently (it is the reference implementation).

Reference parity: the reference embeds wasmtime's cranelift JIT
(src/evaluation/precompiled_policy.rs:46-64); this is the build's native
execution engine for the same role, with the Python interpreter as the
differential oracle (tests/test_native_wasm.py runs both).
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import sys
import sysconfig
import threading
from pathlib import Path

from policy_server_tpu.wasm import interp as _interp
from policy_server_tpu.wasm.binary import ELSE, END, F32, F64, WasmModule
from policy_server_tpu.wasm.interp import (
    Memory,
    WasmDeadlineExceeded,
    WasmFuelExhausted,
    WasmTrap,
)

_REPO_ROOT = Path(__file__).resolve().parents[2]
_SRC = _REPO_ROOT / "csrc" / "wasmint.cpp"

_BLOCK = 0x02
_LOOP = 0x03
_IF = 0x04

_MEM_OPS = set(range(0x28, 0x3F))  # loads + stores (memarg offset in imm)

# f32/f64 immediates and slot values cross the boundary as raw IEEE-754
# bit patterns in 64-bit slots; one spelling per layout (graftcheck NA03)
_SLOT_U64 = struct.Struct("<Q")
_SLOT_I64 = struct.Struct("<q")
_SLOT_F64 = struct.Struct("<d")


class NativeUnsupported(Exception):
    """Module uses a construct the native core does not model."""


# -- library build/load ------------------------------------------------------

_lib: ctypes.CDLL | None = None
_lib_lock = threading.Lock()
_lib_failed = False

_HOSTCB = ctypes.CFUNCTYPE(
    ctypes.c_int32,
    ctypes.c_void_p,  # ctx (unused; dispatch via thread-local)
    ctypes.c_int32,  # func index
    ctypes.POINTER(ctypes.c_uint64),  # args
    ctypes.c_int32,  # nargs
    ctypes.POINTER(ctypes.c_uint64),  # results out
    ctypes.POINTER(ctypes.c_int32),  # nresults out
)


def _build_library() -> Path | None:
    out_dir = _REPO_ROOT / "build"
    out_dir.mkdir(exist_ok=True)
    tag = sysconfig.get_config_var("SOABI") or f"py{sys.version_info[0]}{sys.version_info[1]}"
    # POLICY_SERVER_NATIVE_SAN=asan (tools/sanitize_lane.py): sanitized
    # variant under a distinct name, production cache untouched
    san = os.environ.get("POLICY_SERVER_NATIVE_SAN", "") == "asan"
    out = out_dir / f"wasmint-{tag}{'-san' if san else ''}.so"
    if out.exists() and out.stat().st_mtime >= _SRC.stat().st_mtime:
        return out
    opt = (
        ["-O1", "-g", "-fsanitize=address,undefined",
         "-fno-sanitize-recover=all"]
        if san
        else ["-O2"]
    )
    try:
        subprocess.run(
            ["g++", *opt, "-shared", "-fPIC", "-std=c++17",
             str(_SRC), "-o", str(out)],
            check=True, capture_output=True, timeout=180,
        )
    except Exception:  # noqa: BLE001 — no compiler/feature degrade
        return None
    return out


def _load() -> ctypes.CDLL | None:
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _lib_failed:
            return _lib
        if os.environ.get("PSTPU_NO_NATIVE_WASM") == "1":
            _lib_failed = True
            return None
        path = _build_library()
        if path is None:
            _lib_failed = True
            return None
        try:
            lib = ctypes.CDLL(str(path))
        except OSError:
            _lib_failed = True
            return None
        lib.wasmint_module_new.restype = ctypes.c_void_p
        lib.wasmint_module_free.argtypes = [ctypes.c_void_p]
        lib.wasmint_add_func.restype = ctypes.c_int32
        lib.wasmint_add_func.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
        ]
        lib.wasmint_set_brpool.restype = ctypes.c_int32
        lib.wasmint_set_brpool.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
        ]
        lib.wasmint_add_data.restype = ctypes.c_int32
        lib.wasmint_add_data.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
        ]
        lib.wasmint_inst_new.restype = ctypes.c_void_p
        lib.wasmint_inst_new.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_double, ctypes.c_int32, _HOSTCB,
            ctypes.c_void_p,
        ]
        lib.wasmint_inst_free.argtypes = [ctypes.c_void_p]
        lib.wasmint_set_globals.restype = ctypes.c_int32
        lib.wasmint_set_globals.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64,
        ]
        lib.wasmint_get_global.restype = ctypes.c_int64
        lib.wasmint_get_global.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.wasmint_add_table.restype = ctypes.c_int32
        lib.wasmint_add_table.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
        ]
        lib.wasmint_mem_size.restype = ctypes.c_int64
        lib.wasmint_mem_size.argtypes = [ctypes.c_void_p]
        lib.wasmint_mem_read.restype = ctypes.c_int32
        lib.wasmint_mem_read.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_char_p,
        ]
        lib.wasmint_mem_write.restype = ctypes.c_int32
        lib.wasmint_mem_write.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
        ]
        lib.wasmint_mem_find0.restype = ctypes.c_int64
        lib.wasmint_mem_find0.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.wasmint_fuel_left.restype = ctypes.c_int64
        lib.wasmint_fuel_left.argtypes = [ctypes.c_void_p]
        lib.wasmint_set_fuel.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
        ]
        lib.wasmint_err.restype = ctypes.c_char_p
        lib.wasmint_err.argtypes = [ctypes.c_void_p]
        lib.wasmint_invoke.restype = ctypes.c_int32
        lib.wasmint_invoke.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int32, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int32),
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


# -- module translation ------------------------------------------------------


class _CompiledModule:
    """Shared, immutable native module handle + metadata for instances."""

    def __init__(self, module: WasmModule):
        lib = _load()
        assert lib is not None
        self.module = module
        self.lib = lib

        if any(imp.kind in ("table", "global") for imp in module.imports):
            raise NativeUnsupported("imported table/global")
        n_mem = len(module.memories) + sum(
            1 for i in module.imports if i.kind == "memory"
        )
        if n_mem > 1:
            raise NativeUnsupported("multiple memories")

        # function table: imports first (host), then local functions —
        # the same index space as interp.Instance.funcs
        self.host_types = []  # functype per import (None for local)
        types = module.types
        type_ids: dict = {}

        def type_id(ft) -> int:
            # fixed 32-slot marshalling buffers in the C++ core
            if len(ft.params) > 32 or len(ft.results) > 32:
                raise NativeUnsupported("functype with >32 params/results")
            key = (tuple(ft.params), tuple(ft.results))
            return type_ids.setdefault(key, len(type_ids))

        self.functypes = []
        self.handle = lib.wasmint_module_new()
        if not self.handle:
            raise MemoryError("out of memory creating native module")

        def checked(status: int) -> None:
            # nonzero = allocation failure inside the native core (it must
            # not let bad_alloc unwind through ctypes)
            if status:
                raise MemoryError("out of memory building native module")

        try:
            br_pool: list[int] = []
            translated = []
            for imp in module.imports:
                if imp.kind != "func":
                    continue
                ft = types[imp.desc]
                self.functypes.append(ft)
                translated.append((type_id(ft), len(ft.params),
                                   len(ft.results), 0, 1, None))
            for i, typeidx in enumerate(module.functions):
                ft = types[typeidx]
                self.functypes.append(ft)
                body = module.code[i]
                arrays = self._translate(
                    body.code, types, type_id, br_pool
                )
                translated.append((type_id(ft), len(ft.params),
                                   len(ft.results), len(body.locals), 0,
                                   arrays))
            for tid, np_, nr, nl, is_host, arrays in translated:
                if arrays is None:
                    checked(lib.wasmint_add_func(
                        self.handle, tid, np_, nr, nl, is_host,
                        None, None, None, None, 0,
                    ))
                else:
                    ops, ia, ib, ic = arrays
                    n = len(ops)
                    checked(lib.wasmint_add_func(
                        self.handle, tid, np_, nr, nl, is_host,
                        (ctypes.c_uint32 * n)(*ops),
                        (ctypes.c_int64 * n)(*ia),
                        (ctypes.c_int32 * n)(*ib),
                        (ctypes.c_int32 * n)(*ic),
                        n,
                    ))
            if br_pool:
                checked(lib.wasmint_set_brpool(
                    self.handle, (ctypes.c_int32 * len(br_pool))(*br_pool),
                    len(br_pool),
                ))
            for seg in module.data:
                checked(lib.wasmint_add_data(self.handle, bytes(seg.data),
                                             len(seg.data)))
        except Exception:
            lib.wasmint_module_free(self.handle)
            raise

        self.exports = module.export_map()
        self.n_func_imports = sum(
            1 for i in module.imports if i.kind == "func"
        )

    def __del__(self):  # pragma: no cover — interpreter shutdown ordering
        lib = getattr(self, "lib", None)
        handle = getattr(self, "handle", None)
        if lib is not None and handle:
            try:
                lib.wasmint_module_free(handle)
            except Exception:  # noqa: BLE001
                pass

    @staticmethod
    def _block_arity(bt, types) -> tuple[int, int]:
        if bt is None:
            return 0, 0
        from policy_server_tpu.wasm.binary import F32 as _F32
        from policy_server_tpu.wasm.binary import F64 as _F64
        from policy_server_tpu.wasm.binary import I32 as _I32
        from policy_server_tpu.wasm.binary import I64 as _I64

        if isinstance(bt, int) and bt in (_I32, _I64, _F32, _F64):
            return 0, 1
        ft = types[bt]
        return len(ft.params), len(ft.results)

    def _translate(self, code, types, type_id, br_pool):
        n = len(code)
        ops = [0] * n
        ia = [0] * n
        ib = [0] * n
        ic = [0] * n
        for pc, (op, imm) in enumerate(code):
            ops[pc] = op
            if op in (_BLOCK, _LOOP):
                bt, end = imm
                params, results = self._block_arity(bt, types)
                ia[pc], ib[pc], ic[pc] = end, params, results
            elif op == _IF:
                bt, end, else_idx = imm
                params, results = self._block_arity(bt, types)
                ia[pc] = end
                ib[pc] = -1 if else_idx is None else else_idx
                ic[pc] = (params << 16) | results
            elif op == ELSE:
                ia[pc] = imm if imm is not None else 0
            elif op in (0x0C, 0x0D):
                ia[pc] = imm
            elif op == 0x0E:
                targets, default = imm
                ia[pc] = len(br_pool)
                ib[pc] = len(targets)
                br_pool.extend(targets)
                br_pool.append(default)
            elif op == 0x10:
                ia[pc] = imm
            elif op == 0x11:
                typeidx, table = imm
                ia[pc] = type_id(types[typeidx])
                ib[pc] = table
            elif op in (0x20, 0x21, 0x22, 0x23, 0x24):
                ia[pc] = imm
            elif op in _MEM_OPS:
                ia[pc] = imm
            elif op == 0x41 or op == 0x42:
                ia[pc] = imm
            elif op in (0x43, 0x44):
                ia[pc] = _SLOT_I64.unpack(_SLOT_F64.pack(float(imm)))[0]
            elif op >= 0xFC00:
                sub = op & 0xFF
                if sub in (8, 9):
                    ia[pc] = imm
                elif sub in (0, 1, 2, 3, 4, 5, 6, 7, 10, 11):
                    pass
                else:
                    raise NativeUnsupported(f"extended op {sub}")
            # END / numeric / parameterless ops: no imm
        return ops, ia, ib, ic


def compiled_module(module: WasmModule) -> "_CompiledModule":
    cached = getattr(module, "_native_compiled", None)
    if cached is None:
        # negative results cache too: per-request instantiation must not
        # re-run a full translate-and-reject pass before every fallback
        unsupported = getattr(module, "_native_unsupported", None)
        if unsupported is not None:
            raise NativeUnsupported(unsupported)
        try:
            cached = _CompiledModule(module)
        except NativeUnsupported as e:
            module._native_unsupported = str(e)
            raise
        module._native_compiled = cached
    return cached


# -- instance ---------------------------------------------------------------


class _NativeMemData:
    """The tiny slice of the bytearray API host code touches on
    ``memory.data``: ``find(b"\\x00", start)`` and slicing."""

    def __init__(self, proxy: "_NativeMemory"):
        self._proxy = proxy

    def find(self, needle: bytes, start: int = 0) -> int:
        if needle != b"\x00":
            data = self._proxy.read(0, len(self._proxy))
            return data.find(needle, start)
        return self._proxy._inst._find0(start)

    def __getitem__(self, item):
        # bytearray-faithful indexing: negative indices/bounds wrap from
        # the end and out-of-range slice bounds clamp — host code treating
        # memory.data as a bytearray must not silently read wrong offsets
        n = len(self._proxy)
        if isinstance(item, slice):
            if item.step not in (None, 1):
                raise ValueError(
                    "extended slice steps are not supported on wasm memory"
                )
            start, stop, _ = item.indices(n)
            if stop <= start:
                return b""
            return self._proxy.read(start, stop - start)
        idx = int(item)
        if idx < 0:
            idx += n
        if not 0 <= idx < n:
            raise IndexError("index out of range")
        return self._proxy.read(idx, 1)[0]

    def __len__(self) -> int:
        return len(self._proxy)


class _NativeMemory:
    """interp.Memory surface over the C++-owned linear memory."""

    def __init__(self, inst: "NativeInstance"):
        self._inst = inst

    def __len__(self) -> int:
        return self._inst._mem_size()

    @property
    def pages(self) -> int:
        return self._inst._mem_size() // 65536

    @property
    def data(self) -> _NativeMemData:
        return _NativeMemData(self)

    def read(self, addr: int, n: int) -> bytes:
        return self._inst._mem_read(addr, n)

    def write(self, addr: int, payload: bytes) -> None:
        self._inst._mem_write(addr, payload)


class NativeInstance:
    """interp.Instance drop-in backed by the C++ core. Raises
    NativeUnsupported from the constructor when the module (or its
    imports) cannot run natively — callers fall back to Instance."""

    def __init__(self, module: WasmModule, imports=None, fuel: int | None = 500_000_000):
        self._lib = None  # set late: __del__ must survive partial init
        self._handle = None
        cm = compiled_module(module)
        self.module = module
        self._cm = cm
        lib = cm.lib

        self.deadline = getattr(_interp._ambient, "deadline", None)
        if self.deadline is not None and fuel is None:
            fuel = 1 << 62

        imports = imports or {}
        host_fns = []
        imported_memory: Memory | None = None
        for imp in module.imports:
            provided = (imports.get(imp.module) or {}).get(imp.name)
            if provided is None:
                raise WasmTrap(
                    f"missing import {imp.module}.{imp.name} ({imp.kind})"
                )
            if imp.kind == "func":
                fn = (
                    provided.fn
                    if isinstance(provided, _interp.HostFunc)
                    else provided
                )
                host_fns.append(fn)
            elif imp.kind == "memory":
                if not isinstance(provided, Memory):
                    raise WasmTrap("memory import must be a Memory")
                imported_memory = provided
        self._host_fns = host_fns
        self._host_exc: BaseException | None = None

        # the callback must outlive every invoke on this instance
        self._cb = _HOSTCB(self._dispatch_host)

        mem_pages = 0
        mem_max = -1
        if imported_memory is not None:
            mem_pages = imported_memory.pages
            mem_max = (
                imported_memory.maximum
                if imported_memory.maximum is not None
                else -1
            )
        elif module.memories:
            mem_pages = module.memories[0].minimum
            mem_max = (
                module.memories[0].maximum
                if module.memories[0].maximum is not None
                else -1
            )
        deadline = self.deadline if self.deadline is not None else 0.0
        self._handle = lib.wasmint_inst_new(
            cm.handle, mem_pages, mem_max,
            fuel if fuel is not None else 0,
            1 if fuel is not None else 0,
            deadline, 1 if self.deadline is not None else 0,
            self._cb, None,
        )
        if not self._handle:
            # NULL = allocation failure in the native core (a module may
            # legally declare a ~4 GiB initial memory); fail this request,
            # not the process.
            self._handle = None
            raise WasmTrap("out of memory instantiating module")
        self._lib = lib
        if imported_memory is not None and any(imported_memory.data):
            # the provided Memory's pre-existing content seeds the
            # C++-owned copy (the object itself is discarded — all later
            # access goes through the instance.memory proxy, matching
            # every in-repo creation pattern)
            self._mem_write(0, bytes(imported_memory.data))

        # globals (const-eval like interp.Instance; imports were rejected)
        global_bits = []
        self._global_types = []
        for g in module.globals:
            value = self._const_eval(g.init, global_bits, self._global_types)
            self._global_types.append(g.valtype)
            global_bits.append(self._encode_slot(value, g.valtype))
        if global_bits:
            if lib.wasmint_set_globals(
                self._handle,
                (ctypes.c_uint64 * len(global_bits))(*global_bits),
                len(global_bits),
            ):
                raise WasmTrap("out of memory instantiating module")

        # tables + element segments
        tables = [[-1] * limits.minimum for limits in module.tables]
        for seg in module.elems:
            offset = self._const_eval_plain(seg.offset, global_bits)
            table = tables[seg.table]
            if offset + len(seg.func_indices) > len(table):
                raise WasmTrap("element segment out of bounds")
            for j, fidx in enumerate(seg.func_indices):
                table[offset + j] = fidx
        for t in tables:
            if lib.wasmint_add_table(
                self._handle, (ctypes.c_int32 * len(t))(*t), len(t)
            ):
                raise WasmTrap("out of memory instantiating module")

        # active data segments
        for seg in module.data:
            if seg.offset is None:
                continue
            offset = self._const_eval_plain(seg.offset, global_bits)
            self._mem_write(offset, bytes(seg.data))

        self.memories = (
            [_NativeMemory(self)]
            if (module.memories or imported_memory is not None)
            else []
        )
        self._exports = cm.exports
        if module.start is not None:
            self._invoke_index(module.start, [])

    # -- const-eval (same subset as interp.Instance._const_eval) ----------

    def _const_eval(self, expr, global_bits, global_types):
        stack = []
        for op, imm in expr:
            if op in (0x41, 0x42, 0x43, 0x44):
                stack.append(imm)
            elif op == 0x23:
                stack.append(
                    self._decode_slot(global_bits[imm], global_types[imm])
                )
            else:
                raise WasmTrap(f"unsupported const instr 0x{op:02x}")
        return stack[-1] if stack else 0

    def _const_eval_plain(self, expr, global_bits):
        return self._const_eval(expr, global_bits, self._global_types)

    # -- slot codec --------------------------------------------------------

    @staticmethod
    def _encode_slot(value, valtype) -> int:
        if valtype in (F32, F64):
            return _SLOT_U64.unpack(_SLOT_F64.pack(float(value)))[0]
        return int(value) & 0xFFFFFFFFFFFFFFFF

    @staticmethod
    def _decode_slot(bits: int, valtype):
        if valtype in (F32, F64):
            return _SLOT_F64.unpack(_SLOT_U64.pack(bits & 0xFFFFFFFFFFFFFFFF))[0]
        v = bits & 0xFFFFFFFFFFFFFFFF
        return v - (1 << 64) if v >= (1 << 63) else v

    # -- host dispatch -----------------------------------------------------

    def _dispatch_host(self, _ctx, fidx, args_p, nargs, results_p, nresults_p):
        try:
            ft = self._cm.functypes[fidx]
            fn = self._host_fns[fidx]
            py_args = []
            for k, t in enumerate(ft.params):
                py_args.append(self._decode_slot(args_p[k], t))
            result = fn(self, *py_args)
            if result is None:
                out = []
            elif isinstance(result, tuple):
                out = list(result)
            else:
                out = [result]
            for k, t in enumerate(ft.results):
                results_p[k] = self._encode_slot(out[k], t)
            nresults_p[0] = len(ft.results)
            return 0
        except BaseException as e:  # noqa: BLE001 — crosses the C boundary
            self._host_exc = e
            return 1

    # -- memory ------------------------------------------------------------

    def _mem_size(self) -> int:
        return self._lib.wasmint_mem_size(self._handle)

    def _mem_read(self, addr: int, n: int) -> bytes:
        if n < 0:
            raise WasmTrap("out of bounds memory access")
        buf = ctypes.create_string_buffer(n)
        if self._lib.wasmint_mem_read(self._handle, addr, n, buf):
            raise WasmTrap("out of bounds memory access")
        return buf.raw

    def _mem_write(self, addr: int, payload: bytes) -> None:
        if self._lib.wasmint_mem_write(
            self._handle, addr, bytes(payload), len(payload)
        ):
            raise WasmTrap("out of bounds memory access")

    def _find0(self, start: int) -> int:
        return self._lib.wasmint_mem_find0(self._handle, start)

    # -- public API (interp.Instance surface) ------------------------------

    @property
    def memory(self) -> _NativeMemory:
        return self.memories[0]

    @property
    def fuel(self):
        return self._lib.wasmint_fuel_left(self._handle)

    def invoke(self, name: str, *args):
        exp = self._exports.get(name)
        if exp is None or exp.kind != "func":
            raise WasmTrap(f"no exported function {name!r}")
        return self._invoke_index(exp.index, list(args))

    def global_value(self, name: str):
        exp = self._exports.get(name)
        if exp is None or exp.kind != "global":
            raise WasmTrap(f"no exported global {name!r}")
        bits = self._lib.wasmint_get_global(self._handle, exp.index)
        valtype = (
            self._global_types[exp.index]
            if exp.index < len(self._global_types)
            else None
        )
        return self._decode_slot(bits & 0xFFFFFFFFFFFFFFFF, valtype)

    def _invoke_index(self, findex: int, args: list):
        ft = self._cm.functypes[findex]
        if len(args) != len(ft.params):
            raise WasmTrap(
                f"function expects {len(ft.params)} arguments, got {len(args)}"
            )
        raw = (ctypes.c_uint64 * max(1, len(args)))()
        for k, (v, t) in enumerate(zip(args, ft.params)):
            raw[k] = self._encode_slot(v, t)
        res = (ctypes.c_uint64 * 32)()
        nres = ctypes.c_int32(0)
        self._host_exc = None
        rc = self._lib.wasmint_invoke(
            self._handle, findex, raw, len(args), res, ctypes.byref(nres)
        )
        if rc != 0:
            msg = (self._lib.wasmint_err(self._handle) or b"").decode(
                "utf-8", "replace"
            )
            if rc == 2:
                raise WasmFuelExhausted("wasm fuel exhausted")
            if rc == 3:
                raise WasmDeadlineExceeded("wasm wall-clock deadline exceeded")
            if rc == 4:
                exc = self._host_exc
                self._host_exc = None
                if exc is not None:
                    raise exc
                raise WasmTrap("host function raised")
            raise WasmTrap(msg or "wasm trap")
        return [
            self._decode_slot(res[k], ft.results[k]) for k in range(nres.value)
        ]

    def __del__(self):  # pragma: no cover — interpreter shutdown ordering
        lib, handle = self._lib, self._handle
        if lib is not None and handle:
            try:
                lib.wasmint_inst_free(handle)
            except Exception:  # noqa: BLE001
                pass


def make_instance(module: WasmModule, imports=None, fuel: int | None = 500_000_000):
    """Native instance when possible, Python interp.Instance otherwise —
    the single construction point the waPC/OPA/WASI hosts use."""
    if available():
        try:
            return NativeInstance(module, imports, fuel=fuel)
        except NativeUnsupported:
            pass
    return _interp.Instance(module, imports, fuel=fuel)
