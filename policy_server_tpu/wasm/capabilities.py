"""Default waPC host capabilities — the guest→host surface of the
reference's callback_handler (SURVEY.md §2.2: K8s context lookups,
sigstore verification, OCI digest, DNS, crypto served to wasm guests over
``__host_call``; src/lib.rs:91-125 wires the same set).

TPU-first twist: Kubernetes lookups are answered from the request
payload's ``__context__`` snapshot slice — the SAME capability-filtered,
immutable view the device programs see (context/service.py), so a wasm
guest cannot observe fresher-but-torn cluster state than its co-batched
device rows, and the per-policy contextAwareResources allowlist is
enforced for free (the slice only contains allowlisted kinds).

Capability keys are ``(namespace, operation)`` per the Kubewarden SDK
protocol; payloads are JSON. Network-reaching capabilities (DNS, OCI) are
OPT-IN per policy (``allowNetworkCapabilities: true``) because blocking
egress is invisible to the wasm fuel meter; capabilities that cannot be
served in this environment raise — the guest receives a host error,
never a fabricated answer."""

from __future__ import annotations

import base64
import json
from typing import Any, Callable, Mapping

from policy_server_tpu.context.service import CONTEXT_KEY

HostCapability = Callable[[bytes], bytes]


def _context_of(payload: Any) -> Mapping[str, Any]:
    if isinstance(payload, Mapping):
        ctx = payload.get(CONTEXT_KEY)
        if isinstance(ctx, Mapping):
            return ctx
    return {}


def _resource_key(api_version: str, kind: str) -> str:
    return f"{api_version}/{kind}"


def _kind_items(ctx: Mapping[str, Any], req: Mapping[str, Any]) -> list:
    key = _resource_key(str(req.get("api_version")), str(req.get("kind")))
    items = ctx.get(key)
    return list(items) if isinstance(items, (list, tuple)) else []


def _matches_label_selector(obj: Mapping[str, Any], selector: str | None) -> bool:
    """equality-based selectors only (k=v,k2!=v2); set-based selectors are
    rejected loudly by the caller."""
    if not selector:
        return True
    labels = ((obj.get("metadata") or {}).get("labels")) or {}
    for clause in selector.split(","):
        clause = clause.strip()
        if "!=" in clause:
            k, v = clause.split("!=", 1)
            if labels.get(k.strip()) == v.strip():
                return False
        elif "=" in clause:
            k, v = clause.split("=", 1)
            if labels.get(k.strip().rstrip("=")) != v.strip():
                return False
        elif clause:
            if clause not in labels:
                return False
    return True


DNS_TIMEOUT_SECONDS = 2.0


def kubernetes_capabilities(payload: Any) -> dict[tuple[str, str], HostCapability]:
    """The payload-dependent entries: Kubernetes lookups answered from the
    request's ``__context__`` snapshot slice (capability-filtered by the
    policy's contextAwareResources allowlist)."""
    ctx = _context_of(payload)

    def list_resources_by_namespace(raw: bytes) -> bytes:
        req = json.loads(raw)
        items = [
            o
            for o in _kind_items(ctx, req)
            if ((o.get("metadata") or {}).get("namespace")) == req.get("namespace")
            and _matches_label_selector(o, req.get("label_selector"))
        ]
        return json.dumps(
            {
                "apiVersion": req.get("api_version"),
                "kind": f"{req.get('kind')}List",
                "items": items,
            }
        ).encode()

    def list_all_resources(raw: bytes) -> bytes:
        req = json.loads(raw)
        items = [
            o
            for o in _kind_items(ctx, req)
            if _matches_label_selector(o, req.get("label_selector"))
        ]
        return json.dumps(
            {
                "apiVersion": req.get("api_version"),
                "kind": f"{req.get('kind')}List",
                "items": items,
            }
        ).encode()

    def get_resource(raw: bytes) -> bytes:
        req = json.loads(raw)
        for o in _kind_items(ctx, req):
            meta = o.get("metadata") or {}
            if meta.get("name") == req.get("name") and (
                req.get("namespace") is None
                or meta.get("namespace") == req.get("namespace")
            ):
                return json.dumps(o).encode()
        raise LookupError(
            f"{req.get('kind')} {req.get('namespace')}/{req.get('name')} "
            "not found in the context snapshot (is the kind in this "
            "policy's contextAwareResources allowlist?)"
        )

    return {
        ("kubernetes", "list_resources_by_namespace"): list_resources_by_namespace,
        ("kubernetes", "list_all_resources"): list_all_resources,
        ("kubernetes", "get_resource"): get_resource,
    }


def static_capabilities(
    signature_bundle_source: Callable[[str], Mapping | None] | None = None,
    allow_network: bool = False,
    trust_root: Any = None,
    oci_digest_source: Callable[[str], str] | None = None,
) -> dict[tuple[str, str], HostCapability]:
    """The payload-independent entries — build ONCE per bound policy.
    Network-reaching capabilities (DNS, OCI) are served only when the
    policy opted in via ``allowNetworkCapabilities: true``: a guest must
    not gain blocking egress (which the fuel meter cannot see) by
    default. ``trust_root`` (fetch/keyless.TrustRoot) enables the
    keyless ``v2/verify`` flavor against cosign-style keyless bundles in
    the signature store; without one it rejects in-band.
    ``oci_digest_source`` (image ref → manifest digest; the server wires
    ``Downloader.manifest_digest``) backs ``oci/v1/manifest_digest`` —
    absent, that capability fails loudly."""

    # -- sigstore verify (pub-key flavor; keyless needs Fulcio/Rekor) -------

    def verify_pub_keys_image(raw: bytes) -> bytes:
        if signature_bundle_source is None:
            raise RuntimeError(
                "image signature verification requires a configured "
                "signature store (signatureStore setting)"
            )
        req = json.loads(raw)
        image = str(req.get("image"))
        from policy_server_tpu.policies.images import (
            SignatureEntry,
            _entry_verifies,
        )

        entry = SignatureEntry(
            image_glob="*",
            pub_keys=tuple(req.get("pub_keys") or ()),
            annotations=dict(req.get("annotations") or {}),
        )
        bundle = signature_bundle_source(image)
        trusted = bool(bundle) and _entry_verifies(entry, image, bundle)
        return json.dumps({"is_trusted": trusted, "digest": ""}).encode()

    def verify_keyless_image(raw: bytes) -> bytes:
        """Keyless image verification against the OFFLINE trust root: the
        signature store's bundle carries cosign-style keyless entries
        (cert + rekor scaffolding) whose signed payload binds the image
        reference and manifest digest; identity must match a requested
        (issuer, subject) pair."""
        if trust_root is None:
            raise RuntimeError(
                "sigstore keyless verification requires a trust root "
                "(place trust_root.json in the sigstore cache dir; "
                "fetching the public Fulcio/Rekor TUF root needs network "
                "egress this build does not have)"
            )
        if signature_bundle_source is None:
            raise RuntimeError(
                "image signature verification requires a configured "
                "signature store (signatureStore setting)"
            )
        from policy_server_tpu.fetch.keyless import (
            KeylessError,
            verify_keyless_signature,
        )
        from policy_server_tpu.policies.images import payload_binds_image

        req = json.loads(raw)
        image = str(req.get("image"))
        wanted = [
            (str(k.get("issuer")), str(k.get("subject")))
            for k in req.get("keyless") or []
            if isinstance(k, Mapping)
        ]
        annotations = dict(req.get("annotations") or {})
        bundle = signature_bundle_source(image) or {}
        for entry in bundle.get("keyless") or []:
            try:
                identity, pdoc = verify_keyless_signature(entry, trust_root)
            except KeylessError:
                continue
            # shared v1/v2 trust boundary: type + reference + real digest
            digest = payload_binds_image(pdoc, image)
            if digest is None:
                continue
            try:
                signed_ann = dict(pdoc.get("optional") or {})
            except (TypeError, ValueError):
                continue
            if annotations and any(
                signed_ann.get(k) != v for k, v in annotations.items()
            ):
                continue
            if (identity.issuer, identity.subject) in wanted:
                return json.dumps(
                    {"is_trusted": True, "digest": digest}
                ).encode()
        return json.dumps({"is_trusted": False, "digest": ""}).encode()

    # -- net ---------------------------------------------------------------

    def dns_lookup_host(raw: bytes) -> bytes:
        if not allow_network:
            raise RuntimeError(
                "network capabilities are not enabled for this policy "
                "(set allowNetworkCapabilities: true in its settings)"
            )
        import socket
        from concurrent.futures import Future

        req = json.loads(raw)
        host = str(req.get("host"))
        # bounded: the resolver blocks outside the fuel meter, so a
        # non-resolving host must not stall the serving thread past the
        # deadline
        import threading

        box: Future = Future()

        def resolve() -> None:
            try:
                box.set_result(socket.gethostbyname_ex(host))
            except BaseException as e:  # noqa: BLE001
                box.set_exception(e)

        threading.Thread(target=resolve, daemon=True).start()
        try:
            _, _, ips = box.result(timeout=DNS_TIMEOUT_SECONDS)
        except TimeoutError:
            raise RuntimeError(f"DNS lookup timed out for {host!r}") from None
        except OSError as e:
            raise RuntimeError(f"DNS lookup failed for {host!r}: {e}") from e
        return json.dumps({"ips": ips}).encode()

    # -- crypto ------------------------------------------------------------

    def is_certificate_trusted(raw: bytes) -> bytes:
        """Validity-window + chain-signature check of a PEM/DER cert
        against the supplied chain (the Kubewarden crypto capability)."""
        import datetime

        from cryptography import x509
        from cryptography.exceptions import InvalidSignature

        req = json.loads(raw)

        def load(doc: Mapping[str, Any]) -> x509.Certificate:
            data = doc.get("data")
            if isinstance(data, list):  # SDK encodes bytes as int arrays
                blob = bytes(data)
            else:
                blob = base64.b64decode(data) if isinstance(data, str) else b""
            if doc.get("encoding") == "Der":
                return x509.load_der_x509_certificate(blob)
            return x509.load_pem_x509_certificate(blob)

        try:
            cert = load(req["cert"])
            chain = [load(c) for c in req.get("cert_chain") or []]
        except (KeyError, ValueError, TypeError) as e:
            return json.dumps(
                {"trusted": False, "reason": f"unparsable certificate: {e}"}
            ).encode()

        now = datetime.datetime.now(datetime.timezone.utc)
        not_after = req.get("not_after")
        if not_after:
            try:
                deadline = datetime.datetime.fromisoformat(
                    str(not_after).replace("Z", "+00:00")
                )
            except ValueError:
                return json.dumps(
                    {"trusted": False, "reason": "invalid not_after"}
                ).encode()
            if cert.not_valid_after_utc < deadline:
                return json.dumps(
                    {"trusted": False,
                     "reason": "certificate expires before not_after"}
                ).encode()
        if not (cert.not_valid_before_utc <= now <= cert.not_valid_after_utc):
            return json.dumps(
                {"trusted": False, "reason": "certificate outside validity window"}
            ).encode()
        # chain of signatures: cert signed by chain[0], chain[i] by chain[i+1]
        current = cert
        for issuer in chain:
            try:
                current.verify_directly_issued_by(issuer)
            except (ValueError, TypeError, InvalidSignature) as e:
                return json.dumps(
                    {"trusted": False, "reason": f"chain verification failed: {e}"}
                ).encode()
            current = issuer
        return json.dumps({"trusted": True, "reason": ""}).encode()

    # -- oci ---------------------------------------------------------------

    def manifest_digest(raw: bytes) -> bytes:
        if not allow_network:
            raise RuntimeError(
                "network capabilities are not enabled for this policy "
                "(set allowNetworkCapabilities: true in its settings)"
            )
        if oci_digest_source is None:
            # no registry client was wired in (library callers outside a
            # server bootstrap) — loud, like the reference without its
            # callback handler's registry sources (src/lib.rs:91-125)
            raise RuntimeError(
                "OCI manifest digest lookup requires registry egress, which "
                "this environment does not have"
            )
        doc = json.loads(raw.decode())
        # the SDK sends a bare JSON string; tolerate {"image": ...} too
        image = doc.get("image") if isinstance(doc, Mapping) else doc
        if not isinstance(image, str) or not image:
            raise RuntimeError(
                "manifest_digest request must carry an image reference"
            )
        try:
            digest = oci_digest_source(image)
        except Exception as e:  # noqa: BLE001 — network failure → in-band
            raise RuntimeError(
                f"manifest digest lookup for {image!r} failed: {e}"
            ) from e
        return json.dumps({"digest": digest}).encode()

    return {
        ("kubewarden", "v1/verify"): verify_pub_keys_image,
        ("kubewarden", "v2/verify"): verify_keyless_image,
        ("net", "v1/dns_lookup_host"): dns_lookup_host,
        ("crypto", "v1/is_certificate_trusted"): is_certificate_trusted,
        ("oci", "v1/manifest_digest"): manifest_digest,
        ("oci", "v1/oci_manifest_digest"): manifest_digest,
    }


def build_default_capabilities(
    payload: Any,
    signature_bundle_source: Callable[[str], Mapping | None] | None = None,
    allow_network: bool = False,
    trust_root: Any = None,
    oci_digest_source: Callable[[str], str] | None = None,
) -> dict[tuple[str, str], HostCapability]:
    """Full table for one request (tests and one-off callers; the serving
    path hoists static_capabilities per policy and merges only the
    kubernetes closures per request)."""
    return {
        **static_capabilities(
            signature_bundle_source, allow_network, trust_root=trust_root,
            oci_digest_source=oci_digest_source,
        ),
        **kubernetes_capabilities(payload),
    }
