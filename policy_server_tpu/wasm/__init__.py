"""WebAssembly execution substrate (host-side).

The reference's entire evaluation engine is per-request wasm under
wasmtime (src/evaluation/precompiled_policy.rs:46-64,
src/evaluation/evaluation_environment.rs:513-543). This package provides
the TPU build's host-side counterpart — an independent wasm MVP
interpreter plus the policy ABI hosts (OPA/Gatekeeper, waPC) — serving
two roles:

* **multi-ABI policy execution**: ``.wasm`` policy payloads run host-side
  per request (the escape hatch the device path falls back to, and the
  execution path for policies outside the predicate IR);
* **non-circular correctness oracle**: differential tests run REAL wasm
  modules (including upstream-compiled Gatekeeper policies) against the
  JAX backend — the oracle no longer interprets the same IR the device
  compiles, so a shared lowering bug cannot pass silently.

No wasmtime/compiler exists in this environment; execution is a pure
Python stack interpreter (wasm/interp.py). Throughput is irrelevant for
both roles — correctness and isolation are what count (the interpreter
enforces memory bounds, type-checked indirect calls, and a fuel limit as
the epoch-interruption analog, src/lib.rs:176-190)."""

from policy_server_tpu.wasm.binary import WasmModule, decode_module
from policy_server_tpu.wasm.interp import Instance, WasmTrap

__all__ = ["WasmModule", "decode_module", "Instance", "WasmTrap"]
