"""WebAssembly binary decoder (MVP + sign-extension + saturating
truncation + bulk memory — the feature set clang/LLVM and the OPA wasm
compiler emit for policy modules).

Decodes a ``.wasm`` byte string into a :class:`WasmModule` with function
bodies as flat instruction lists whose structured control flow
(block/loop/if) is pre-resolved to jump targets, so the interpreter
(wasm/interp.py) executes with simple program-counter jumps."""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any

MAGIC = b"\x00asm\x01\x00\x00\x00"

# value types
I32, I64, F32, F64 = 0x7F, 0x7E, 0x7D, 0x7C
FUNCREF = 0x70
VALTYPES = {I32: "i32", I64: "i64", F32: "f32", F64: "f64"}


class WasmDecodeError(Exception):
    pass


class _Reader:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes, pos: int = 0):
        self.data = data
        self.pos = pos

    def byte(self) -> int:
        b = self.data[self.pos]
        self.pos += 1
        return b

    def bytes(self, n: int) -> bytes:
        out = self.data[self.pos : self.pos + n]
        if len(out) != n:
            raise WasmDecodeError("unexpected end of section")
        self.pos += n
        return out

    def u32(self) -> int:
        result = shift = 0
        while True:
            b = self.byte()
            result |= (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                return result

    def s_leb(self, bits: int) -> int:
        result = shift = 0
        while True:
            b = self.byte()
            result |= (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                if shift < bits and b & 0x40:
                    result |= -(1 << shift)
                return result

    def s32(self) -> int:
        return self.s_leb(32)

    def s64(self) -> int:
        return self.s_leb(64)

    def f32(self) -> float:
        return struct.unpack("<f", self.bytes(4))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self.bytes(8))[0]

    def name(self) -> str:
        return self.bytes(self.u32()).decode("utf-8")

    def done(self) -> bool:
        return self.pos >= len(self.data)


@dataclass(frozen=True)
class FuncType:
    params: tuple[int, ...]
    results: tuple[int, ...]


@dataclass
class Limits:
    minimum: int
    maximum: int | None


@dataclass
class Import:
    module: str
    name: str
    kind: str  # func | table | memory | global
    desc: Any  # typeidx | Limits | (valtype, mutable)


@dataclass
class Export:
    name: str
    kind: str
    index: int


@dataclass
class GlobalDef:
    valtype: int
    mutable: bool
    init: list  # const expr instruction list


@dataclass
class ElemSegment:
    table: int
    offset: list  # const expr
    func_indices: list[int]


@dataclass
class DataSegment:
    memory: int
    offset: list | None  # const expr; None = passive
    data: bytes


@dataclass
class FuncBody:
    typeidx: int
    locals: list[int]  # flattened local valtypes (excluding params)
    code: list  # flat [(op, imm), ...] with targets resolved


@dataclass
class WasmModule:
    types: list[FuncType] = field(default_factory=list)
    imports: list[Import] = field(default_factory=list)
    functions: list[int] = field(default_factory=list)  # typeidx per local fn
    tables: list[Limits] = field(default_factory=list)
    memories: list[Limits] = field(default_factory=list)
    globals: list[GlobalDef] = field(default_factory=list)
    exports: list[Export] = field(default_factory=list)
    start: int | None = None
    elems: list[ElemSegment] = field(default_factory=list)
    code: list[FuncBody] = field(default_factory=list)
    data: list[DataSegment] = field(default_factory=list)

    def export_map(self) -> dict[str, Export]:
        return {e.name: e for e in self.exports}

    @property
    def num_imported_funcs(self) -> int:
        return sum(1 for i in self.imports if i.kind == "func")


# ---------------------------------------------------------------------------
# Instruction decoding
# ---------------------------------------------------------------------------

# opcodes with no immediate are decoded as (op, None). The interpreter
# dispatches on the integer opcode; 0xFC-prefixed ops are encoded as
# 0xFC00 | sub.

_BLOCK_OPS = {0x02, 0x03, 0x04}  # block, loop, if
END, ELSE = 0x0B, 0x05

_MEM_OPS = set(range(0x28, 0x3F))  # loads/stores (memarg immediates)


def _decode_blocktype(r: _Reader) -> Any:
    b = r.data[r.pos]
    if b == 0x40:
        r.pos += 1
        return None  # empty
    if b in VALTYPES:
        r.pos += 1
        return b  # single result valtype
    return r.s32()  # type index (multi-value)


def _decode_instr(op: int, r: _Reader):
    if op in _BLOCK_OPS:
        return (op, _decode_blocktype(r))
    if op in (END, ELSE, 0x00, 0x01, 0x0F, 0x1A, 0x1B):  # end/else/unreachable/nop/return/drop/select
        return (op, None)
    if op in (0x0C, 0x0D):  # br, br_if
        return (op, r.u32())
    if op == 0x0E:  # br_table
        n = r.u32()
        targets = [r.u32() for _ in range(n)]
        default = r.u32()
        return (op, (targets, default))
    if op == 0x10:  # call
        return (op, r.u32())
    if op == 0x11:  # call_indirect
        typeidx = r.u32()
        table = r.u32()
        return (op, (typeidx, table))
    if op in (0x20, 0x21, 0x22, 0x23, 0x24):  # local/global get/set/tee
        return (op, r.u32())
    if op in _MEM_OPS:  # memarg: align, offset
        r.u32()
        return (op, r.u32())  # keep offset only
    if op in (0x3F, 0x40):  # memory.size / memory.grow
        r.byte()
        return (op, None)
    if op == 0x41:
        return (op, r.s32())
    if op == 0x42:
        return (op, r.s64())
    if op == 0x43:
        return (op, r.f32())
    if op == 0x44:
        return (op, r.f64())
    if 0x45 <= op <= 0xC4:  # numeric ops + sign extension, no immediates
        return (op, None)
    if op == 0xFC:
        sub = r.u32()
        code = 0xFC00 | sub
        if sub in (0, 1, 2, 3, 4, 5, 6, 7):  # saturating truncations
            return (code, None)
        if sub == 8:  # memory.init
            seg = r.u32()
            r.byte()
            return (code, seg)
        if sub == 9:  # data.drop
            return (code, r.u32())
        if sub == 10:  # memory.copy
            r.byte()
            r.byte()
            return (code, None)
        if sub == 11:  # memory.fill
            r.byte()
            return (code, None)
        if sub == 12:  # table.init
            seg = r.u32()
            table = r.u32()
            return (code, (seg, table))
        if sub == 13:  # elem.drop
            return (code, r.u32())
        if sub == 14:  # table.copy
            return (code, (r.u32(), r.u32()))
        if sub in (15, 16, 17):  # table.grow/size/fill
            return (code, r.u32())
        raise WasmDecodeError(f"unsupported 0xFC opcode {sub}")
    raise WasmDecodeError(f"unsupported opcode 0x{op:02x}")


def decode_body(r: _Reader) -> list:
    """Decode one function body to a flat instruction list with control
    targets resolved:

    * ``block``/``if`` imm → (blocktype, end_index, else_index|None)
    * ``loop`` imm → (blocktype, end_index)
    * ``end``/``else`` stay as markers (interpreter skips them; ``else``
      jumps to its block's end when reached from the then-branch)
    """
    code: list = []
    stack: list[tuple[int, int]] = []  # (opcode, index)
    while True:
        op = r.byte()
        if op == END:
            if not stack:
                code.append((END, None))
                return code
            code.append((END, None))
            start_op, idx = stack.pop()
            kind, imm = code[idx]
            end_index = len(code) - 1
            if start_op == 0x04:  # if: (bt, end, else)
                bt, _, else_idx = imm
                code[idx] = (kind, (bt, end_index, else_idx))
                if else_idx is not None:
                    code[else_idx] = (ELSE, end_index)
            elif start_op == 0x02:  # block
                bt, _ = imm
                code[idx] = (kind, (bt, end_index))
            else:  # loop
                bt, _ = imm
                code[idx] = (kind, (bt, end_index))
            continue
        if op == ELSE:
            # find the innermost if and record the else position
            start_op, idx = stack[-1]
            if start_op != 0x04:
                raise WasmDecodeError("else outside if")
            kind, (bt, e, _none) = code[idx]
            code.append((ELSE, None))  # target patched at END
            code[idx] = (kind, (bt, e, len(code) - 1))
            continue
        instr = _decode_instr(op, r)
        if op in _BLOCK_OPS:
            bt = instr[1]
            if op == 0x04:
                code.append((op, (bt, -1, None)))
            else:
                code.append((op, (bt, -1)))
            stack.append((op, len(code) - 1))
        else:
            code.append(instr)


def decode_const_expr(r: _Reader) -> list:
    """Constant expressions (globals / offsets): a short instruction run
    terminated by END."""
    out = []
    while True:
        op = r.byte()
        if op == END:
            return out
        out.append(_decode_instr(op, r))


# ---------------------------------------------------------------------------
# Module decoding
# ---------------------------------------------------------------------------


def decode_module(data: bytes) -> WasmModule:
    if data[:8] != MAGIC:
        raise WasmDecodeError("not a wasm v1 module")
    m = WasmModule()
    r = _Reader(data, 8)
    while r.pos < len(data):
        sid = r.byte()
        size = r.u32()
        section = _Reader(r.bytes(size))
        if sid == 1:  # types
            for _ in range(section.u32()):
                if section.byte() != 0x60:
                    raise WasmDecodeError("expected functype")
                params = tuple(section.byte() for _ in range(section.u32()))
                results = tuple(section.byte() for _ in range(section.u32()))
                m.types.append(FuncType(params, results))
        elif sid == 2:  # imports
            for _ in range(section.u32()):
                module = section.name()
                name = section.name()
                kind = section.byte()
                if kind == 0:
                    m.imports.append(Import(module, name, "func", section.u32()))
                elif kind == 1:
                    section.byte()  # reftype
                    m.imports.append(
                        Import(module, name, "table", _limits(section))
                    )
                elif kind == 2:
                    m.imports.append(
                        Import(module, name, "memory", _limits(section))
                    )
                elif kind == 3:
                    vt = section.byte()
                    mut = section.byte()
                    m.imports.append(
                        Import(module, name, "global", (vt, bool(mut)))
                    )
                else:
                    raise WasmDecodeError(f"bad import kind {kind}")
        elif sid == 3:  # functions
            m.functions = [section.u32() for _ in range(section.u32())]
        elif sid == 4:  # tables
            for _ in range(section.u32()):
                section.byte()  # reftype
                m.tables.append(_limits(section))
        elif sid == 5:  # memories
            for _ in range(section.u32()):
                m.memories.append(_limits(section))
        elif sid == 6:  # globals
            for _ in range(section.u32()):
                vt = section.byte()
                mut = section.byte()
                m.globals.append(
                    GlobalDef(vt, bool(mut), decode_const_expr(section))
                )
        elif sid == 7:  # exports
            kinds = {0: "func", 1: "table", 2: "memory", 3: "global"}
            for _ in range(section.u32()):
                name = section.name()
                kind = kinds[section.byte()]
                m.exports.append(Export(name, kind, section.u32()))
        elif sid == 8:  # start
            m.start = section.u32()
        elif sid == 9:  # elements
            for _ in range(section.u32()):
                flags = section.u32()
                if flags == 0:
                    offset = decode_const_expr(section)
                    funcs = [section.u32() for _ in range(section.u32())]
                    m.elems.append(ElemSegment(0, offset, funcs))
                elif flags == 2:
                    table = section.u32()
                    offset = decode_const_expr(section)
                    if section.byte() != 0:
                        raise WasmDecodeError("unsupported elemkind")
                    funcs = [section.u32() for _ in range(section.u32())]
                    m.elems.append(ElemSegment(table, offset, funcs))
                else:
                    raise WasmDecodeError(
                        f"unsupported element segment flags {flags}"
                    )
        elif sid == 10:  # code
            for _ in range(section.u32()):
                body_size = section.u32()
                body = _Reader(section.bytes(body_size))
                locals_out: list[int] = []
                for _ in range(body.u32()):
                    n = body.u32()
                    vt = body.byte()
                    locals_out.extend([vt] * n)
                code = decode_body(body)
                m.code.append(FuncBody(0, locals_out, code))
        elif sid == 11:  # data
            for _ in range(section.u32()):
                flags = section.u32()
                if flags == 0:
                    offset = decode_const_expr(section)
                    m.data.append(
                        DataSegment(0, offset, section.bytes(section.u32()))
                    )
                elif flags == 1:  # passive
                    m.data.append(
                        DataSegment(0, None, section.bytes(section.u32()))
                    )
                elif flags == 2:
                    mem = section.u32()
                    offset = decode_const_expr(section)
                    m.data.append(
                        DataSegment(mem, offset, section.bytes(section.u32()))
                    )
                else:
                    raise WasmDecodeError(f"bad data segment flags {flags}")
        # sid 0 (custom) and 12 (datacount) carry nothing we execute
    # bind typeidx into FuncBody for convenience
    for i, body in enumerate(m.code):
        body.typeidx = m.functions[i]
    return m


def ensure_module(wasm: "bytes | WasmModule") -> WasmModule:
    """bytes→decode, WasmModule→passthrough: the one definition of the
    polymorphism every ABI host accepts."""
    return wasm if isinstance(wasm, WasmModule) else decode_module(wasm)


def _limits(r: _Reader) -> Limits:
    flags = r.byte()
    minimum = r.u32()
    maximum = r.u32() if flags & 1 else None
    return Limits(minimum, maximum)
