"""OPA / Gatekeeper wasm ABI host.

Runs policies compiled by OPA's wasm backend (Rego → wasm) — the
``PolicyExecutionMode::OpaGatekeeper`` path of the reference's engine
(burrego; exercised by the embedded gatekeeper fixtures,
src/evaluation/evaluation_environment.rs:727-731). The module imports
``env.memory`` plus the ``opa_builtin{0..4}``/``opa_abort`` host calls and
exports the classic OPA eval surface (opa_malloc / opa_json_parse /
opa_eval_ctx_* / eval / opa_json_dump).

Evaluation protocol (one fresh instance per evaluation, mirroring the
reference's rehydrate-per-request isolation,
evaluation_environment.rs:76-84):

1. parse ``data`` and ``input`` JSON into OPA values on the module heap,
2. build an eval context, bind input/data/entrypoint,
3. ``eval(ctx)``, read the result set via ``opa_json_dump``.

Gatekeeper verdict mapping (burrego semantics): the entrypoint yields
``violations`` objects; no violations ⇒ allowed, otherwise the ``msg``
fields aggregate into the rejection message."""

from __future__ import annotations

import json
from typing import Any, Callable, Mapping

from policy_server_tpu.wasm import builtins as builtins_mod
from policy_server_tpu.wasm.binary import WasmModule, ensure_module
from policy_server_tpu.wasm.native_exec import make_instance
from policy_server_tpu.wasm.interp import Instance, Memory, WasmTrap


class OpaError(Exception):
    pass


def _read_cstring(instance: Instance, addr: int) -> bytes:
    mem = instance.memory.data
    end = mem.find(b"\x00", addr)
    if end < 0:
        raise WasmTrap("unterminated string")
    return bytes(mem[addr:end])


class OpaPolicy:
    """A decoded OPA wasm policy; instantiate_and_eval per request."""

    def __init__(self, wasm_bytes: bytes | WasmModule, fuel: int | None = 50_000_000):
        self.module: WasmModule = ensure_module(wasm_bytes)
        self.fuel = fuel
        exports = {e.name for e in self.module.exports}
        required = {"opa_malloc", "opa_json_parse", "opa_json_dump", "eval",
                    "opa_eval_ctx_new", "opa_eval_ctx_set_input",
                    "opa_eval_ctx_set_data", "opa_eval_ctx_get_result"}
        missing = required - exports
        if missing:
            raise OpaError(f"not an OPA wasm module (missing {sorted(missing)})")
        # id → name for host-dispatched builtins, from the module's own
        # builtins() declaration (the OPA wasm ABI contract; burrego reads
        # the same export). Resolved once at load; {} when the module
        # declares none.
        self._builtin_names: dict[int, str] = {}
        if "builtins" in exports:
            declared = self.builtins()
            self._builtin_names = {
                int(v): str(k) for k, v in declared.items()
            }

    # -- instantiation ------------------------------------------------------

    def _imports(self) -> tuple[dict, list[str]]:
        aborts: list[str] = []

        def opa_abort(instance: Instance, addr: int) -> None:
            message = _read_cstring(instance, addr).decode("utf-8", "replace")
            aborts.append(message)
            raise WasmTrap(f"opa_abort: {message}")

        def opa_println(instance: Instance, addr: int) -> None:
            pass  # debugging aid in the guest; ignored

        def builtin(n: int) -> Callable:
            def call(instance: Instance, builtin_id: int, ctx: int, *args: int) -> int:
                name = self._builtin_names.get(builtin_id)
                impl = builtins_mod.REGISTRY.get(name) if name else None
                if impl is None:
                    label = f"{builtin_id} ({name})" if name else str(builtin_id)
                    raise WasmTrap(
                        f"OPA builtin {label} (arity {n}) is not provided "
                        "by this host"
                    )
                # decode each arg through the guest's own serializer, run
                # the host implementation, re-enter the guest to intern the
                # result (burrego round-trips values the same way). EVERY
                # host failure — BuiltinError, arity-mismatch TypeError,
                # decode errors from a hostile module — must surface as a
                # WasmTrap so the policy layer maps it to an in-band
                # rejection, never a crashed request handler.
                try:
                    decoded = [self._dump_value(instance, a) for a in args]
                    result = impl(*decoded)
                except WasmTrap:
                    raise
                except Exception as e:
                    raise WasmTrap(f"OPA builtin {name}: {e}") from e
                return self._parse_value(instance, result)

            return call

        env: dict[str, Any] = {
            "opa_abort": opa_abort,
            "opa_println": opa_println,
        }
        for n in range(5):
            env[f"opa_builtin{n}"] = builtin(n)
        for imp in self.module.imports:
            if imp.kind == "memory" and imp.module == "env":
                env["memory"] = Memory(imp.desc)
        return {"env": env}, aborts

    def instantiate(self) -> Instance:
        imports, _aborts = self._imports()
        return make_instance(self.module, imports, fuel=self.fuel)

    # -- host-builtin value marshalling -------------------------------------

    @staticmethod
    def _dump_value(instance: Instance, addr: int) -> Any:
        """Guest OPA value → decoded JSON, via the guest's opa_json_dump."""
        dumped = instance.invoke("opa_json_dump", addr)[0]
        return json.loads(_read_cstring(instance, dumped).decode())

    @staticmethod
    def _parse_value(instance: Instance, value: Any) -> int:
        """Host JSON value → guest OPA value address."""
        raw = json.dumps(value).encode()
        addr = instance.invoke("opa_malloc", len(raw))[0]
        instance.memory.write(addr, raw)
        parsed = instance.invoke("opa_json_parse", addr, len(raw))[0]
        if parsed == 0:
            raise WasmTrap("opa_json_parse failed for builtin result")
        return parsed

    # -- evaluation ---------------------------------------------------------

    def evaluate(
        self,
        input_doc: Any,
        data_doc: Any = None,
        entrypoint: int = 0,
    ) -> Any:
        """One isolated evaluation → the decoded OPA result set."""
        inst = self.instantiate()

        def load_json(doc: Any) -> int:
            raw = json.dumps(doc if doc is not None else {}).encode()
            addr = inst.invoke("opa_malloc", len(raw))[0]
            inst.memory.write(addr, raw)
            value = inst.invoke("opa_json_parse", addr, len(raw))[0]
            if value == 0:
                raise OpaError("opa_json_parse failed")
            return value

        data_addr = load_json(data_doc)
        input_addr = load_json(input_doc)
        ctx = inst.invoke("opa_eval_ctx_new")[0]
        inst.invoke("opa_eval_ctx_set_data", ctx, data_addr)
        inst.invoke("opa_eval_ctx_set_input", ctx, input_addr)
        if "opa_eval_ctx_set_entrypoint" in {e.name for e in self.module.exports}:
            inst.invoke("opa_eval_ctx_set_entrypoint", ctx, entrypoint)
        rc = inst.invoke("eval", ctx)
        if rc and rc[0] != 0:
            raise OpaError(f"eval returned {rc[0]}")
        result_addr = inst.invoke("opa_eval_ctx_get_result", ctx)[0]
        dumped = inst.invoke("opa_json_dump", result_addr)[0]
        return json.loads(_read_cstring(inst, dumped).decode())

    def entrypoints(self) -> dict[str, int]:
        inst = self.instantiate()
        addr = inst.invoke("entrypoints")[0]
        dumped = inst.invoke("opa_json_dump", addr)[0]
        return json.loads(_read_cstring(inst, dumped).decode())

    def builtins(self) -> dict[str, int]:
        inst = self.instantiate()
        addr = inst.invoke("builtins")[0]
        dumped = inst.invoke("opa_json_dump", addr)[0]
        return json.loads(_read_cstring(inst, dumped).decode())


# ---------------------------------------------------------------------------
# Gatekeeper verdict mapping (burrego parity)
# ---------------------------------------------------------------------------


def gatekeeper_validate(
    policy: OpaPolicy, admission_request: Mapping[str, Any],
    parameters: Mapping[str, Any] | None = None,
) -> tuple[bool, str | None]:
    """Evaluate a Gatekeeper-compiled policy against one AdmissionReview
    request → (allowed, message). Gatekeeper policies see
    ``input.review`` + ``input.parameters`` and emit ``violations``
    (burrego's Gatekeeper evaluator contract)."""
    result = policy.evaluate(
        {"review": dict(admission_request), "parameters": dict(parameters or {})}
    )
    violations: list = []
    for entry in result if isinstance(result, list) else []:
        r = entry.get("result")
        if isinstance(r, Mapping):
            violations.extend(r.get("violations") or [])
        elif isinstance(r, list):
            violations.extend(r)
    if not violations:
        return True, None
    msgs = [
        str(v.get("msg", v)) if isinstance(v, Mapping) else str(v)
        for v in violations
    ]
    return False, "; ".join(msgs)
