"""waPC host — the guest-call protocol Kubewarden policies speak.

Reference parity: the reference's engine instantiates a fresh wasm guest
per evaluation and drives it through waPC
(evaluation_environment.rs:513-543; waPC is the ABI of
PolicyExecutionMode::KubewardenWapc modules). The protocol:

* host invokes the guest export ``__guest_call(op_len, payload_len)``;
* the guest allocates buffers and calls back ``__guest_request`` for the
  host to write the operation name and payload;
* the guest answers via ``__guest_response`` / ``__guest_error``;
* ``__host_call`` is the guest→host capability channel (the reference's
  callback_handler seam) — host capabilities are provided as Python
  callables keyed by (namespace, operation).

Kubewarden operations: ``validate`` (payload ``{"request":…,
"settings":…}`` → ``{"accepted":…}``), ``validate_settings``,
``protocol_version``.

Flat payload ABI: policies authored in this repo's WAT subset cannot
carry a full JSON parser, so the host ALSO offers ``validate`` with a
flattened payload (``flatten_payload``: ``key\\0value\\0…`` entries) when
the guest exports the marker global ``__flat_abi``. The flattener is a
direct JSON walk, deliberately independent of ops/codec.py's tensor
encoding — that independence is what makes the wasm differential oracle
non-circular."""

from __future__ import annotations

import json
from typing import Any, Callable, Mapping

from policy_server_tpu.wasm.binary import ensure_module
from policy_server_tpu.wasm.native_exec import make_instance
from policy_server_tpu.wasm.interp import Instance, WasmTrap

HostCapability = Callable[[bytes], bytes]


class WapcError(Exception):
    pass


def _escape_map_key(k: str) -> str:
    """Mapping keys are escaped so a rendered mapping key can never
    contain ``#`` (list-index marker) or ``.`` (path separator): any
    ``#`` in a flat key provably marks list traversal, and any ``.``
    provably separates path segments. Without this, a mapping key like
    ``spec.hostNetwork`` or ``containers.#0.securityContext.privileged``
    (dots inside ONE key) would render byte-identical to a real
    structural path and spoof the WAT oracles' matchers. The tensor
    codec treats such keys as single opaque keys (trie walk is
    structural), so the oracles must see them the same way."""
    return k.replace("%", "%25").replace("#", "%23").replace(".", "%2E")


def flatten_payload(doc: Any, prefix: str = "") -> bytes:
    """JSON → ``key\\0value\\0`` entries (sorted, deterministic).

    Keys: dotted paths; list indices render as ``#N`` segments; mapping
    keys are %-escaped so they can never start with ``#`` (see
    ``_escape_map_key``).

    Values are TYPE-TAGGED with one leading byte so wasm policies can
    tell a JSON string from other scalars rendering to the same text
    (``true`` vs ``"true"`` — an untagged ABI made bool-valued policy
    checks spoofable by strings): ``s`` string (raw bytes follow),
    ``b`` bool (``btrue``/``bfalse``), ``z`` null, ``n`` number
    (JSON text follows)."""
    entries: list[tuple[str, str]] = []

    def walk(node: Any, path: str) -> None:
        if isinstance(node, Mapping):
            for k in sorted(node):
                ek = _escape_map_key(str(k))
                walk(node[k], f"{path}.{ek}" if path else ek)
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                walk(v, f"{path}.#{i}" if path else f"#{i}")
        else:
            if node is True:
                text = "btrue"
            elif node is False:
                text = "bfalse"
            elif node is None:
                text = "z"
            elif isinstance(node, str):
                text = "s" + node
            else:
                text = "n" + json.dumps(node)
            if "\x00" in path or "\x00" in text:
                # NUL is legal inside JSON strings but is this ABI's entry
                # framing: letting it through would let a request string
                # forge extra key/value entries (policy bypass)
                raise WapcError(
                    "NUL byte in payload key or value cannot be framed in "
                    "the flat ABI"
                )
            entries.append((path, text))

    walk(doc, prefix)
    out = bytearray()
    for k, v in entries:
        out += k.encode() + b"\x00" + v.encode() + b"\x00"
    return bytes(out)


class WapcGuest:
    """A decoded waPC policy module; every call() gets a fresh instance
    (per-request isolation, evaluation_environment.rs:76-84)."""

    def __init__(
        self,
        wasm_bytes: bytes,
        host_capabilities: Mapping[tuple[str, str], HostCapability] | None = None,
        fuel: int | None = 50_000_000,
    ):
        self.module = ensure_module(wasm_bytes)
        self.host_capabilities = dict(host_capabilities or {})
        self.fuel = fuel
        exports = self.module.export_map()
        if "__guest_call" not in exports:
            raise WapcError("not a waPC module (missing __guest_call)")
        self.flat_abi = "__flat_abi" in exports

    def call(
        self,
        operation: str,
        payload: bytes,
        host_capabilities: Mapping[tuple[str, str], HostCapability] | None = None,
    ) -> bytes:
        capabilities = (
            self.host_capabilities
            if host_capabilities is None
            else {**self.host_capabilities, **host_capabilities}
        )
        op_bytes = operation.encode()
        state: dict[str, Any] = {"response": None, "error": None,
                                 "host_response": b"", "host_error": b""}

        def guest_request(inst: Instance, op_ptr: int, payload_ptr: int):
            inst.memory.write(op_ptr, op_bytes)
            inst.memory.write(payload_ptr, payload)

        def guest_response(inst: Instance, ptr: int, length: int):
            state["response"] = inst.memory.read(ptr, length)

        def guest_error(inst: Instance, ptr: int, length: int):
            state["error"] = inst.memory.read(ptr, length)

        def host_call(inst, bd_ptr, bd_len, ns_ptr, ns_len, op_ptr, op_len,
                      ptr, length):
            ns = inst.memory.read(ns_ptr, ns_len).decode()
            op = inst.memory.read(op_ptr, op_len).decode()
            fn = capabilities.get((ns, op))
            if fn is None:
                state["host_error"] = (
                    f"host capability {ns}/{op} not available".encode()
                )
                return 0
            try:
                state["host_response"] = fn(inst.memory.read(ptr, length))
                return 1
            except Exception as e:  # noqa: BLE001 — surfaced to the guest
                state["host_error"] = str(e).encode()
                return 0

        def host_response_len(inst):
            return len(state["host_response"])

        def host_response(inst, ptr):
            inst.memory.write(ptr, state["host_response"])

        def host_error_len(inst):
            return len(state["host_error"])

        def host_error(inst, ptr):
            inst.memory.write(ptr, state["host_error"])

        def console_log(inst, ptr, length):
            pass

        imports = {
            "wapc": {
                "__guest_request": guest_request,
                "__guest_response": guest_response,
                "__guest_error": guest_error,
                "__host_call": host_call,
                "__host_response_len": host_response_len,
                "__host_response": host_response,
                "__host_error_len": host_error_len,
                "__host_error": host_error,
                "__console_log": console_log,
            }
        }
        inst = make_instance(self.module, imports, fuel=self.fuel)
        ok = inst.invoke("__guest_call", len(op_bytes), len(payload))
        if not ok or not ok[0]:
            err = state["error"] or b"guest call failed"
            raise WapcError(err.decode("utf-8", "replace"))
        if state["response"] is None:
            raise WapcError("guest returned no response")
        return state["response"]


class KubewardenWapcPolicy:
    """Kubewarden validate/validate_settings over a waPC guest."""

    def __init__(
        self,
        wasm_bytes: bytes,
        host_capabilities: Mapping[tuple[str, str], HostCapability] | None = None,
        fuel: int | None = 50_000_000,
    ):
        self.guest = WapcGuest(wasm_bytes, host_capabilities, fuel=fuel)

    def validate(
        self,
        request: Mapping[str, Any],
        settings: Mapping[str, Any] | None,
        host_capabilities: Mapping[tuple[str, str], HostCapability] | None = None,
    ) -> dict:
        if self.guest.flat_abi:
            payload = flatten_payload(
                {"request": dict(request), "settings": dict(settings or {})}
            )
        else:
            payload = json.dumps(
                {"request": dict(request), "settings": dict(settings or {})}
            ).encode()
        return _json_object(
            self.guest.call("validate", payload, host_capabilities)
        )

    def validate_settings(self, settings: Mapping[str, Any] | None) -> dict:
        if self.guest.flat_abi:
            payload = flatten_payload(dict(settings or {}))
        else:
            payload = json.dumps(dict(settings or {})).encode()
        return _json_object(self.guest.call("validate_settings", payload))


def _json_object(raw: bytes) -> dict:
    """Guest responses must be JSON objects; anything else is a guest
    protocol error (mapped to an in-band 500 upstream)."""
    try:
        doc = json.loads(raw)
    except ValueError as e:
        raise WapcError(f"guest response is not valid JSON: {e}") from e
    if not isinstance(doc, dict):
        raise WapcError("guest response is not a JSON object")
    return doc
