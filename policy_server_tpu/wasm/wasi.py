"""WASI policy execution (PolicyExecutionMode::Wasi).

The reference runs WASI policies as wasmtime command modules: the policy
is a program whose argv selects the operation, the request/settings JSON
arrives on stdin, and the verdict JSON leaves on stdout
(src/evaluation/precompiled_policy.rs:46-64; SURVEY.md §2.2
PolicyExecutionMode row). This module provides:

* a ``wasi_snapshot_preview1`` host — the import set command modules
  need (fd_read/fd_write over in-memory stdio, args/environ, proc_exit,
  clocks, random), with ENOSYS stubs for the rest so modules linking
  more of libc still instantiate;
* :class:`WasiPolicy` — one fresh instance per evaluation (the
  rehydrate-per-request isolation, evaluation_environment.rs:76-84),
  protocol: ``argv = [name, operation]``, stdin =
  ``{"request":…, "settings":…}``, stdout = the Kubewarden
  ValidationResponse JSON (same shape as the waPC protocol, wasm/wapc.py).

Fuel bounds runaway guests exactly like the other ABIs.
"""

from __future__ import annotations

import json
import time
from typing import Any, Mapping

from policy_server_tpu.wasm.binary import WasmModule, ensure_module
from policy_server_tpu.wasm.native_exec import make_instance
from policy_server_tpu.wasm.interp import Instance, Memory, WasmTrap

ERRNO_SUCCESS = 0
ERRNO_BADF = 8
ERRNO_NOSYS = 52


class WasiError(Exception):
    pass


class WasiExit(Exception):
    """proc_exit: terminates the guest with an exit code."""

    def __init__(self, code: int):
        super().__init__(f"proc_exit({code})")
        self.code = code


class _WasiState:
    """Per-instantiation stdio + argv."""

    def __init__(self, argv: list[str], stdin: bytes):
        self.argv = [a.encode() for a in argv]
        self.stdin = stdin
        self.stdin_pos = 0
        self.stdout = bytearray()
        self.stderr = bytearray()


def _u32(mem: Memory, addr: int) -> int:
    return int.from_bytes(mem.read(addr, 4), "little")


def _store_u32(mem: Memory, addr: int, value: int) -> None:
    mem.write(addr, (value & 0xFFFFFFFF).to_bytes(4, "little"))


def _store_u64(mem: Memory, addr: int, value: int) -> None:
    mem.write(addr, (value & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "little"))


def make_wasi_imports(state: _WasiState) -> dict[str, Any]:
    """The wasi_snapshot_preview1 function table over one state."""

    def fd_read(inst: Instance, fd: int, iovs: int, iovs_len: int, nread_ptr: int) -> int:
        if fd != 0:
            return ERRNO_BADF
        mem = inst.memory
        total = 0
        for i in range(iovs_len):
            buf_ptr = _u32(mem, iovs + 8 * i)
            buf_len = _u32(mem, iovs + 8 * i + 4)
            remaining = len(state.stdin) - state.stdin_pos
            n = min(buf_len, remaining)
            if n:
                mem.write(
                    buf_ptr, state.stdin[state.stdin_pos : state.stdin_pos + n]
                )
                state.stdin_pos += n
                total += n
            if n < buf_len:
                break
        _store_u32(mem, nread_ptr, total)
        return ERRNO_SUCCESS

    def fd_write(inst: Instance, fd: int, ciovs: int, ciovs_len: int, nwritten_ptr: int) -> int:
        if fd not in (1, 2):
            return ERRNO_BADF
        sink = state.stdout if fd == 1 else state.stderr
        mem = inst.memory
        total = 0
        for i in range(ciovs_len):
            buf_ptr = _u32(mem, ciovs + 8 * i)
            buf_len = _u32(mem, ciovs + 8 * i + 4)
            sink.extend(mem.read(buf_ptr, buf_len))
            total += buf_len
        _store_u32(mem, nwritten_ptr, total)
        return ERRNO_SUCCESS

    def args_sizes_get(inst: Instance, argc_ptr: int, buf_size_ptr: int) -> int:
        _store_u32(inst.memory, argc_ptr, len(state.argv))
        _store_u32(
            inst.memory, buf_size_ptr, sum(len(a) + 1 for a in state.argv)
        )
        return ERRNO_SUCCESS

    def args_get(inst: Instance, argv_ptr: int, buf_ptr: int) -> int:
        mem = inst.memory
        offset = buf_ptr
        for i, arg in enumerate(state.argv):
            _store_u32(mem, argv_ptr + 4 * i, offset)
            mem.write(offset, arg + b"\x00")
            offset += len(arg) + 1
        return ERRNO_SUCCESS

    def environ_sizes_get(inst: Instance, count_ptr: int, size_ptr: int) -> int:
        _store_u32(inst.memory, count_ptr, 0)
        _store_u32(inst.memory, size_ptr, 0)
        return ERRNO_SUCCESS

    def environ_get(inst: Instance, env_ptr: int, buf_ptr: int) -> int:
        return ERRNO_SUCCESS

    def proc_exit(inst: Instance, code: int) -> None:
        raise WasiExit(code)

    def fd_close(inst: Instance, fd: int) -> int:
        return ERRNO_SUCCESS

    def fd_fdstat_get(inst: Instance, fd: int, ptr: int) -> int:
        if fd > 2:
            return ERRNO_BADF
        # filetype=character_device(2), zero flags/rights
        inst.memory.write(ptr, bytes([2]) + b"\x00" * 23)
        return ERRNO_SUCCESS

    def fd_seek(inst: Instance, fd: int, offset: int, whence: int, new_ptr: int) -> int:
        return 29  # ESPIPE: stdio is not seekable

    def fd_prestat_get(inst: Instance, fd: int, ptr: int) -> int:
        return ERRNO_BADF  # no preopened directories

    def fd_prestat_dir_name(inst: Instance, fd: int, ptr: int, n: int) -> int:
        return ERRNO_BADF

    def random_get(inst: Instance, buf: int, n: int) -> int:
        # deterministic stream: policies must not branch on entropy, and
        # reproducible evaluations keep the differential harness exact
        inst.memory.write(buf, bytes(((i * 97 + 13) & 0xFF) for i in range(n)))
        return ERRNO_SUCCESS

    def clock_time_get(inst: Instance, clock_id: int, precision: int, out_ptr: int) -> int:
        _store_u64(inst.memory, out_ptr, time.time_ns())
        return ERRNO_SUCCESS

    def sched_yield(inst: Instance) -> int:
        return ERRNO_SUCCESS

    return {
        "fd_read": fd_read,
        "fd_write": fd_write,
        "args_sizes_get": args_sizes_get,
        "args_get": args_get,
        "environ_sizes_get": environ_sizes_get,
        "environ_get": environ_get,
        "proc_exit": proc_exit,
        "fd_close": fd_close,
        "fd_fdstat_get": fd_fdstat_get,
        "fd_seek": fd_seek,
        "fd_prestat_get": fd_prestat_get,
        "fd_prestat_dir_name": fd_prestat_dir_name,
        "random_get": random_get,
        "clock_time_get": clock_time_get,
        "sched_yield": sched_yield,
    }


def _nosys_stub(name: str):
    def stub(inst: Instance, *args: int) -> int:
        return ERRNO_NOSYS

    stub.__name__ = f"wasi_{name}_nosys"
    return stub


class WasiPolicy:
    """A decoded WASI command module; fresh instance per evaluation."""

    def __init__(self, wasm_bytes: bytes | WasmModule, fuel: int | None = 50_000_000):
        self.module: WasmModule = ensure_module(wasm_bytes)
        self.fuel = fuel
        exports = {e.name for e in self.module.exports}
        if "_start" not in exports:
            raise WasiError("not a WASI command module (no _start export)")
        self.name = "wasi-policy"

    def _run(self, operation: str, payload: Mapping[str, Any]) -> dict:
        state = _WasiState(
            argv=[self.name, operation],
            stdin=json.dumps(payload, separators=(",", ":")).encode(),
        )
        table = make_wasi_imports(state)
        imports: dict[str, Any] = {}
        for imp in self.module.imports:
            if imp.module == "wasi_snapshot_preview1" and imp.kind == "func":
                imports.setdefault(imp.module, {})[imp.name] = (
                    table.get(imp.name) or _nosys_stub(imp.name)
                )
            elif imp.kind == "memory":
                imports.setdefault(imp.module, {})[imp.name] = Memory(imp.desc)
        inst = make_instance(self.module, imports, fuel=self.fuel)
        code = 0
        try:
            inst.invoke("_start")
        except WasiExit as e:
            code = e.code
        if code != 0:
            err = bytes(state.stderr).decode("utf-8", "replace").strip()
            raise WasiError(
                f"wasi policy exited with code {code}"
                + (f": {err}" if err else "")
            )
        out = bytes(state.stdout).decode("utf-8", "replace").strip()
        if not out:
            raise WasiError("wasi policy produced no output")
        try:
            doc = json.loads(out)
        except json.JSONDecodeError as e:
            raise WasiError(f"wasi policy output is not JSON: {e}") from e
        if not isinstance(doc, dict):
            raise WasiError("wasi policy output must be a JSON object")
        return doc

    def validate(
        self, request: Mapping[str, Any], settings: Mapping[str, Any] | None
    ) -> dict:
        return self._run(
            "validate",
            {"request": dict(request), "settings": dict(settings or {})},
        )

    def validate_settings(self, settings: Mapping[str, Any] | None) -> dict:
        return self._run("validate-settings", dict(settings or {}))
