"""Lock-order sanitizer ("tsan-lite") — dynamic checker 5 of graftcheck.

Opt-in instrumentation of every ``threading.Lock``/``RLock`` the
package creates: when armed, the factory is monkeypatched so locks
constructed from ``policy_server_tpu`` code return a :class:`SanLock`
wrapper that records, per thread, the stack of locks currently held.
Each acquisition while another lock is held adds an edge to a global
acquired-after graph (with the acquisition stack captured the first
time an edge is seen); a cycle in that graph is a lock-order inversion
— two threads interleaving those chains can deadlock. Releases also
record hold durations, and holds longer than the deadline threshold
(``GRAFTCHECK_LOCKSAN_HOLD_MS``, default 2000 ms — the policy
deadline) are reported as long-hold events.

Zero-cost off: nothing in this module runs unless :func:`install` is
called (``tests/conftest.py`` arms it when ``GRAFTCHECK_LOCKSAN=1`` is
set, which is how ``make chaos`` runs). Production code never imports
it.

Lock identity is the CREATION SITE (``file:line`` of the constructor
call), not the instance: the order contract "batcher stats lock before
breaker lock" is a property of the code paths, so all instances created
at one site share a graph node. Consequences, both deliberate:

* same-site edges (instance A's lock taken while instance B's lock
  from the same line is held) are ignored — hand-over-hand over
  same-class instances would need an instance-level order we don't
  impose anywhere;
* an inversion between two sites is reported even if the two observed
  chains used different instances — that is still a latent deadlock
  for the instance-sharing case and exactly what a static reviewer
  would flag.

Only locks created from package code are wrapped (the factory inspects
the caller's frame once, at construction): stdlib internals (logging,
queue, ThreadPoolExecutor) keep native locks, so arming does not
perturb unrelated machinery.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

_installed = False
# the sanitizer's own state lock — constructed at import time, which is
# necessarily before install() can patch the factory, so it is a native
# lock and never self-instruments
_state_lock = threading.Lock()
_edges: dict[tuple[str, str], list[str]] = {}  # guarded-by: _state_lock
_long_holds: list[tuple[str, float, list[str]]] = []  # guarded-by: _state_lock
_max_hold: dict[str, float] = {}  # guarded-by: _state_lock
_acquisitions = 0  # guarded-by: _state_lock

_tls = threading.local()

HOLD_THRESHOLD_MS = float(os.environ.get("GRAFTCHECK_LOCKSAN_HOLD_MS", "2000"))
_PKG_DIR = os.path.dirname(os.path.abspath(__file__))


def _held_stack() -> list:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def _site_of_caller() -> str | None:
    """file:line of the frame constructing the lock, package-relative;
    None when the constructor is not package code."""
    frame = sys._getframe(2)
    fname = frame.f_code.co_filename
    if not fname.startswith(_PKG_DIR) or fname == __file__:
        return None
    rel = os.path.relpath(fname, os.path.dirname(_PKG_DIR))
    return f"{rel}:{frame.f_lineno}"


class SanLock:
    """Instrumented wrapper with the threading.Lock surface the package
    uses (acquire/release/locked/context manager)."""

    __slots__ = ("_lock", "site", "_acquired_at", "_reentrant", "_depth")

    def __init__(self, real, site: str, reentrant: bool):
        self._lock = real
        self.site = site
        self._acquired_at = 0.0
        self._reentrant = reentrant
        # re-entrancy depth (RLock): hold time must span OUTER acquire
        # to OUTER release, so the timestamp is taken only at 0 -> 1 and
        # the duration only at 1 -> 0. Same-thread only by definition of
        # re-entrancy, so a plain int is safe.
        self._depth = 0

    # -- threading.Lock surface -------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._on_acquired()
        return got

    def release(self) -> None:
        self._on_release()
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "SanLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # -- recording ---------------------------------------------------------

    def _on_acquired(self) -> None:
        held = _held_stack()
        now = time.monotonic()
        new_edges = []
        for prior in held:
            if prior.site != self.site:
                new_edges.append((prior.site, self.site))
        self._depth += 1
        if self._depth == 1:
            self._acquired_at = now
        held.append(self)
        with _state_lock:
            global _acquisitions
            _acquisitions += 1
            for edge in new_edges:
                if edge not in _edges:
                    _edges[edge] = traceback.format_stack(
                        sys._getframe(2), limit=12
                    )

    def _on_release(self) -> None:
        held = _held_stack()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break
        self._depth -= 1
        if self._depth > 0:  # inner re-entrant release: not the hold end
            return
        dur_ms = (time.monotonic() - self._acquired_at) * 1000.0
        with _state_lock:
            if dur_ms > _max_hold.get(self.site, 0.0):
                _max_hold[self.site] = dur_ms
            if dur_ms > HOLD_THRESHOLD_MS:
                _long_holds.append(
                    (
                        self.site,
                        dur_ms,
                        traceback.format_stack(sys._getframe(2), limit=8),
                    )
                )


def _factory(real_ctor, reentrant: bool):
    def make(*args, **kwargs):
        site = _site_of_caller()
        real = real_ctor(*args, **kwargs)
        if site is None:
            return real
        return SanLock(real, site, reentrant)

    return make


def install() -> None:
    """Arm the sanitizer: patch threading.Lock/RLock so package-created
    locks are instrumented. Idempotent."""
    global _installed
    if _installed:
        return
    threading.Lock = _factory(_REAL_LOCK, False)  # type: ignore[assignment]
    threading.RLock = _factory(_REAL_RLOCK, True)  # type: ignore[assignment]
    _installed = True


def uninstall() -> None:
    global _installed
    threading.Lock = _REAL_LOCK  # type: ignore[assignment]
    threading.RLock = _REAL_RLOCK  # type: ignore[assignment]
    _installed = False


def installed() -> bool:
    return _installed


def reset() -> None:
    with _state_lock:
        _edges.clear()
        _long_holds.clear()
        _max_hold.clear()
        global _acquisitions
        _acquisitions = 0


def cycles() -> list[list[str]]:
    """Cycles (lock-order inversions) in the acquired-after graph, each
    as the sorted list of member sites (SCCs with >1 node)."""
    from policy_server_tpu.utils.graphs import strongly_connected_components

    with _state_lock:
        graph: dict[str, set[str]] = {}
        for a, b in _edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
    return strongly_connected_components(graph)


def report() -> dict:
    """Snapshot for the end-of-session reporter: edge count, inversions
    (with first-seen acquisition stacks), long holds, max hold times."""
    found = cycles()
    with _state_lock:
        edge_list = sorted(_edges)
        inversion_stacks = {}
        for cyc in found:
            members = set(cyc)
            for edge in edge_list:
                if edge[0] in members and edge[1] in members:
                    inversion_stacks[edge] = _edges[edge]
        return {
            "acquisitions": _acquisitions,
            "edges": edge_list,
            "inversions": found,
            "inversion_stacks": inversion_stacks,
            "long_holds": list(_long_holds),
            "max_hold_ms": dict(sorted(_max_hold.items())),
        }


def format_report(rep: dict | None = None) -> str:
    rep = rep or report()
    lines = [
        "graftcheck locksan: "
        f"{rep['acquisitions']} acquisitions, "
        f"{len(rep['edges'])} distinct order edges, "
        f"{len(rep['inversions'])} inversion(s), "
        f"{len(rep['long_holds'])} long hold(s) "
        f"(> {HOLD_THRESHOLD_MS:.0f} ms)",
    ]
    for cyc in rep["inversions"]:
        lines.append("  INVERSION (potential deadlock): " + " <-> ".join(cyc))
        for edge, stack in rep["inversion_stacks"].items():
            if edge[0] in cyc and edge[1] in cyc:
                lines.append(f"    first {edge[0]} -> {edge[1]} at:")
                lines.extend("      " + ln.rstrip() for ln in stack[-3:])
    for site, dur, _stack in rep["long_holds"][:10]:
        lines.append(f"  LONG HOLD: {site} held {dur:.0f} ms")
    return "\n".join(lines)
