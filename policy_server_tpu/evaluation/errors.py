"""Evaluation errors.

Reference parity: src/evaluation/errors.rs:6-24 (``EvaluationError``
variants). The API layer maps PolicyNotFound → 404 and everything else →
500 (src/api/handlers.rs:321-342); PolicyInitialization errors become
in-band 500 rejections (src/api/service.rs:78-94).
"""

from __future__ import annotations


class EvaluationError(Exception):
    pass


class InvalidPolicyId(EvaluationError):
    pass


class PolicyNotFoundError(EvaluationError):
    def __init__(self, policy_id: str):
        super().__init__(f"policy not found: {policy_id}")
        self.policy_id = policy_id


class PolicyInitializationError(EvaluationError):
    def __init__(self, policy_id: str, message: str):
        super().__init__(message)
        self.policy_id = policy_id


class BootstrapFailure(EvaluationError):
    pass


class ExecutionDeadlineExceeded(EvaluationError):
    """The batched analog of wasmtime epoch interruption
    (reference lib.rs:176-190; rejection message
    'execution deadline exceeded', integration_test.rs:417)."""

    def __init__(self) -> None:
        super().__init__("execution deadline exceeded")
