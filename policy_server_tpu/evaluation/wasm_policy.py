"""Wasm policy modules — multi-ABI policy execution (SURVEY.md §2.2).

The reference executes every policy as wasm under wasmtime, speaking one
of several ABIs (PolicyExecutionMode: Kubewarden waPC, OPA, OPA-Gatekeeper
— precompiled_policy.rs:46-64). This module is the TPU build's
counterpart: a fetched ``.wasm`` artifact becomes a
:class:`WasmPolicyModule` whose bound program carries a
``host_evaluator`` — the evaluation environment routes such policies
through host-side wasm execution (wasm/interp.py) instead of the fused
device program. Wasm policies are the generality escape hatch; the
predicate-IR path remains the TPU fast path.

ABI detection is by exports: ``__guest_call`` ⇒ waPC (Kubewarden
protocol, wasm/wapc.py); ``opa_eval_ctx_new`` ⇒ OPA/Gatekeeper
(wasm/opa.py); ``_start`` ⇒ WASI command module (wasm/wasi.py:
argv-selected operation, request JSON on stdin, verdict JSON on
stdout). A runaway module exhausts its interpreter fuel and is
rejected in-band with the reference's "execution deadline exceeded"
message (the epoch-interruption analog, src/lib.rs:176-190)."""

from __future__ import annotations

from typing import Any, Mapping

from policy_server_tpu.ops.compiler import PolicyProgram, Rule
from policy_server_tpu.ops.ir import false
from policy_server_tpu.policies.base import SettingsValidationResponse
from policy_server_tpu.wasm.binary import decode_module
from policy_server_tpu.wasm.interp import (
    WasmFuelExhausted,
    WasmTrap,
    deadline_scope,
)
from policy_server_tpu.wasm.opa import OpaError, OpaPolicy, gatekeeper_validate
from policy_server_tpu.wasm.wapc import KubewardenWapcPolicy, WapcError
from policy_server_tpu.wasm.wasi import WasiError, WasiPolicy

DEADLINE_MESSAGE = "execution deadline exceeded"


class WasmPolicyModule:
    """PolicyModule protocol over a wasm payload (multi-ABI)."""

    def __init__(
        self,
        wasm_bytes: bytes,
        name: str,
        digest: str,
        fuel: int | None = 50_000_000,
        wall_clock_budget: float | None = 2.0,
    ):
        self.name = name
        self.digest = digest
        # Per-evaluation wall-clock budget — the epoch-interruption analog
        # (reference --policy-timeout default 2 s, src/cli.rs:164-169).
        # The environment builder syncs this to the server's configured
        # policy timeout; None disables. Instance state, not a process
        # global: each server's environment owns its modules the way each
        # reference PolicyServer owns its wasmtime Engine epoch.
        self.wall_clock_budget = wall_clock_budget
        # offline sigstore trust root (fetch/keyless.TrustRoot) for the
        # keyless v2/verify host capability; synced by the environment
        # builder from the server's sigstore cache dir
        self.trust_root = None
        # image ref → manifest digest callable backing oci/v1/
        # manifest_digest (Downloader.manifest_digest); synced by the
        # environment builder from the server's registry client
        self.oci_digest_source = None
        module = decode_module(wasm_bytes)  # decoded ONCE, shared by hosts
        exports = {e.name for e in module.exports}
        if "__guest_call" in exports:
            self.abi = "wapc"
            self._wapc = KubewardenWapcPolicy(module, fuel=fuel)
        elif "opa_eval_ctx_new" in exports:
            self.abi = "opa-gatekeeper"
            self._opa = OpaPolicy(module, fuel=fuel)
        elif "_start" in exports:
            self.abi = "wasi"
            self._wasi = WasiPolicy(module, fuel=fuel)
            self._wasi.name = name
        else:
            raise WasmTrap(
                f"wasm module {name!r} speaks no supported policy ABI "
                "(expected waPC __guest_call, OPA opa_eval_ctx_new, or "
                "WASI _start exports)"
            )
        # waPC and WASI guests may return a mutated object; whether the
        # operator permits it is gated by allowedToMutate like any policy
        self.mutating = self.abi in ("wapc", "wasi")

    def build(self, settings: Mapping[str, Any]) -> PolicyProgram:
        from policy_server_tpu.context.service import CONTEXT_KEY
        from policy_server_tpu.policies.base import SettingsError
        from policy_server_tpu.wasm.capabilities import (
            kubernetes_capabilities,
            static_capabilities,
        )

        bound_settings = dict(settings or {})
        # optional signature store: backs the sigstore host capability
        # (kubewarden/v1/verify) the way the policy's own settings back
        # verify-image-signatures; fail fast on a non-string value rather
        # than surfacing a misleading "no store configured" per request
        bundle_source = None
        store = bound_settings.get("signatureStore")
        if store is not None:
            if not isinstance(store, str):
                raise SettingsError(
                    "setting 'signatureStore' must be a directory path"
                )
            from policy_server_tpu.policies.images import file_bundle_source

            bundle_source = file_bundle_source(store)
        allow_network = bool(bound_settings.get("allowNetworkCapabilities"))
        # payload-independent capability entries: built ONCE per policy
        statics = static_capabilities(
            bundle_source, allow_network, trust_root=self.trust_root,
            oci_digest_source=self.oci_digest_source,
        )

        def evaluate(payload: Any) -> Mapping[str, Any]:
            try:
                return _evaluate_inner(payload)
            except WasmFuelExhausted:
                # fuel OR wall-clock deadline (WasmDeadlineExceeded)
                return {
                    "accepted": False,
                    "message": DEADLINE_MESSAGE,
                    "code": 500,
                }
            except (WasmTrap, WapcError, OpaError, WasiError) as e:
                # guest crash → in-band rejection, mirroring the reference
                # surfacing wasm errors as 500 responses
                return {
                    "accepted": False,
                    "message": f"wasm policy execution failed: {e}",
                    "code": 500,
                }

        def _evaluate_inner(payload: Any) -> Mapping[str, Any]:
            with deadline_scope(self.wall_clock_budget):
                if self.abi == "wapc":
                    # the guest gets the REQUEST; cluster state is served
                    # on demand through the kubernetes capabilities from
                    # the same snapshot slice (no bulk context in-payload)
                    request_doc = (
                        {k: v for k, v in payload.items() if k != CONTEXT_KEY}
                        if isinstance(payload, Mapping)
                        else payload
                    )
                    return self._wapc.validate(
                        request_doc,
                        bound_settings,
                        host_capabilities={
                            **statics,
                            **kubernetes_capabilities(payload),
                        },
                    )
                if self.abi == "wasi":
                    request_doc = (
                        {k: v for k, v in payload.items() if k != CONTEXT_KEY}
                        if isinstance(payload, Mapping)
                        else payload
                    )
                    return self._wasi.validate(request_doc, bound_settings)
                allowed, message = gatekeeper_validate(
                    self._opa, payload, parameters=bound_settings
                )
                return {"accepted": allowed, "message": message}

        return PolicyProgram(
            # the device program never decides for wasm policies; the
            # false() rule keeps the fused-program machinery total
            rules=(Rule("wasm-host-executed", false(), "unreachable"),),
            host_evaluator=evaluate,
        )

    def validate_settings(
        self, settings: Mapping[str, Any]
    ) -> SettingsValidationResponse:
        if self.abi in ("wapc", "wasi"):
            host = self._wapc if self.abi == "wapc" else self._wasi
            try:
                # settings validation runs at boot but executes GUEST code:
                # it needs the same wall-clock cut as evaluate(), or a
                # spinning validate_settings hangs environment build
                with deadline_scope(self.wall_clock_budget):
                    doc = host.validate_settings(dict(settings or {}))
            except WasmFuelExhausted:
                return SettingsValidationResponse(
                    valid=False,
                    message=f"settings validation failed: {DEADLINE_MESSAGE}",
                )
            except (WasmTrap, WapcError, OpaError, WasiError) as e:
                return SettingsValidationResponse(
                    valid=False, message=f"settings validation failed: {e}"
                )
            return SettingsValidationResponse(
                valid=bool(doc.get("valid")), message=doc.get("message")
            )
        return SettingsValidationResponse(valid=True, message=None)
